"""Optimizer algorithms (reference ``python/paddle/optimizer/``: sgd.py,
momentum.py, adam.py, adamw.py, lamb.py, …). Each defines only the functional
core; the fused-step machinery lives in the base class."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer

__all__ = [
    "SGD",
    "Momentum",
    "Adagrad",
    "Adadelta",
    "RMSProp",
    "Adam",
    "AdamW",
    "Adamax",
    "NAdam",
    "RAdam",
    "Lamb",
    "ASGD",
    "Rprop",
]


class SGD(Optimizer):
    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def init_state(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        v = self._momentum * state["velocity"] + grad
        if self._use_nesterov:
            new_param = param - lr * (grad + self._momentum * v)
        else:
            new_param = param - lr * v
        return new_param, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def init_state(self, param):
        return {"moment": jnp.full_like(param, self._initial)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        m = state["moment"] + jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def init_state(self, param):
        return {"avg_squared_grad": jnp.zeros_like(param), "avg_squared_update": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        g2 = self._rho * state["avg_squared_grad"] + (1 - self._rho) * jnp.square(grad)
        upd = (
            jnp.sqrt(state["avg_squared_update"] + self._epsilon)
            / jnp.sqrt(g2 + self._epsilon)
            * grad
        )
        u2 = self._rho * state["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        return param - lr * upd, {"avg_squared_grad": g2, "avg_squared_update": u2}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_state(self, param):
        st = {"mean_square": jnp.zeros_like(param), "momentum": jnp.zeros_like(param)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(param)
        return st

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(grad)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * grad / denom
        new_state["momentum"] = mom
        return param - mom, new_state


class Adam(Optimizer):
    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        lazy_mode=False,
        multi_precision=False,
        use_multi_tensor=False,
        amsgrad=False,
        name=None,
    ):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def init_state(self, param):
        st = {"moment1": jnp.zeros_like(param), "moment2": jnp.zeros_like(param)}
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros_like(param)
        return st

    def _adam_update(self, param, grad, state, lr, step, decoupled_wd, l2_wd):
        if l2_wd:
            grad = grad + l2_wd * param
        b1 = jnp.asarray(self._beta1, param.dtype)
        b2 = jnp.asarray(self._beta2, param.dtype)
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(grad)
        t = step.astype(param.dtype)
        m_hat = m / (1 - jnp.power(b1, t))
        if self._amsgrad:
            v_max = jnp.maximum(state["moment2_max"], v)
            v_hat = v_max / (1 - jnp.power(b2, t))
        else:
            v_hat = v / (1 - jnp.power(b2, t))
        upd = m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        if decoupled_wd:
            upd = upd + decoupled_wd * param
        new_param = param - lr * upd
        new_state = {"moment1": m, "moment2": v}
        if self._amsgrad:
            new_state["moment2_max"] = v_max
        return new_param, new_state

    def update(self, param, grad, state, *, lr, step, weight_decay):
        # paddle Adam applies weight_decay as L2 regularization (coupled)
        return self._adam_update(param, grad, state, lr, step, 0.0, weight_decay)


class AdamW(Adam):
    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        parameters=None,
        weight_decay=0.01,
        lr_ratio=None,
        apply_decay_param_fun=None,
        grad_clip=None,
        lazy_mode=False,
        multi_precision=False,
        amsgrad=False,
        name=None,
    ):
        super().__init__(
            learning_rate, beta1, beta2, epsilon, parameters,
            weight_decay=weight_decay, grad_clip=grad_clip,
            multi_precision=multi_precision, amsgrad=amsgrad, name=name,
        )
        self._apply_decay_param_fun = apply_decay_param_fun

    def _param_weight_decay(self, p, wd):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return 0.0
        return wd

    def update(self, param, grad, state, *, lr, step, weight_decay):
        # decoupled weight decay (AdamW)
        return self._adam_update(param, grad, state, lr, step, weight_decay, 0.0)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def init_state(self, param):
        return {"moment": jnp.zeros_like(param), "inf_norm": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        m = self._beta1 * state["moment"] + (1 - self._beta1) * grad
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(grad) + self._epsilon)
        t = step.astype(param.dtype)
        new_param = param - lr / (1 - jnp.power(self._beta1, t)) * m / u
        return new_param, {"moment": m, "inf_norm": u}


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8, momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._momentum_decay = momentum_decay

    def init_state(self, param):
        return {
            "moment1": jnp.zeros_like(param),
            "moment2": jnp.zeros_like(param),
            "mu_product": jnp.ones((), param.dtype),
        }

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        t = step.astype(param.dtype)
        mu_t = self._beta1 * (1 - 0.5 * jnp.power(0.96, t * self._momentum_decay))
        mu_t1 = self._beta1 * (1 - 0.5 * jnp.power(0.96, (t + 1) * self._momentum_decay))
        mu_prod = state["mu_product"] * mu_t
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * grad
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(grad)
        m_hat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * grad / (1 - mu_prod)
        v_hat = v / (1 - jnp.power(self._beta2, t))
        new_param = param - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_param, {"moment1": m, "moment2": v, "mu_product": mu_prod}


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, param):
        return {"moment1": jnp.zeros_like(param), "moment2": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        if weight_decay:
            grad = grad + weight_decay * param
        t = step.astype(param.dtype)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * grad
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(grad)
        m_hat = m / (1 - jnp.power(self._beta1, t))
        rho_inf = 2.0 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * jnp.power(self._beta2, t) / (1 - jnp.power(self._beta2, t))
        r = jnp.sqrt(
            ((rho_t - 4) * (rho_t - 2) * rho_inf)
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8)
        )
        v_hat = jnp.sqrt(v / (1 - jnp.power(self._beta2, t)))
        adaptive = r * m_hat / (v_hat + self._epsilon)
        new_param = jnp.where(rho_t > 5.0, param - lr * adaptive, param - lr * m_hat)
        return new_param, {"moment1": m, "moment2": v}


class Lamb(Optimizer):
    """LAMB (reference ``python/paddle/optimizer/lamb.py`` +
    ``distributed_fused_lamb`` fused kernel): layerwise-adaptive Adam for
    large-batch training."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, param):
        return {"moment1": jnp.zeros_like(param), "moment2": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        t = step.astype(param.dtype)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * grad
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(grad)
        m_hat = m / (1 - jnp.power(self._beta1, t))
        v_hat = v / (1 - jnp.power(self._beta2, t))
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + weight_decay * param
        w_norm = jnp.sqrt(jnp.sum(jnp.square(param)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return param - lr * trust * r, {"moment1": m, "moment2": v}


class ASGD(SGD):
    pass


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0), parameters=None, etas=(0.5, 1.2), grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._lr_range = learning_rate_range
        self._etas = etas

    def init_state(self, param):
        return {
            "prev_grad": jnp.zeros_like(param),
            "lr": jnp.full_like(param, float(self._learning_rate) if not callable(self._learning_rate) else 0.001),
        }

    def update(self, param, grad, state, *, lr, step, weight_decay):
        sign = jnp.sign(grad * state["prev_grad"])
        factor = jnp.where(sign > 0, self._etas[1], jnp.where(sign < 0, self._etas[0], 1.0))
        new_lr = jnp.clip(state["lr"] * factor, self._lr_range[0], self._lr_range[1])
        grad = jnp.where(sign < 0, jnp.zeros_like(grad), grad)
        new_param = param - jnp.sign(grad) * new_lr
        return new_param, {"prev_grad": grad, "lr": new_lr}


class Ftrl(Optimizer):
    """FTRL-Proximal (reference ``ftrl op``, ``paddle/phi/kernels/*/ftrl*``):
    the classic online-learning rule with per-coordinate adaptive lr and
    L1/L2 proximal shrinkage."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def init_state(self, param):
        return {"squared": jnp.zeros_like(param), "linear": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        n, z = state["squared"], state["linear"]
        new_n = n + jnp.square(grad)
        p = -self._lr_power
        sigma = (jnp.power(new_n, p) - jnp.power(n, p)) / lr
        new_z = z + grad - sigma * param
        denom = jnp.power(new_n, p) / lr + 2.0 * self._l2
        new_param = jnp.where(
            jnp.abs(new_z) > self._l1,
            -(new_z - jnp.sign(new_z) * self._l1) / denom,
            jnp.zeros_like(param),
        )
        return new_param, {"squared": new_n, "linear": new_z}


class DecayedAdagrad(Optimizer):
    """Decayed Adagrad (reference ``decayed_adagrad op``): Adagrad whose
    accumulator decays, preventing the lr from vanishing."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._decay, self._epsilon = decay, epsilon

    def init_state(self, param):
        return {"moment": jnp.zeros_like(param)}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        m = self._decay * state["moment"] + (1 - self._decay) * jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference ``dpsgd op``): per-step gradient
    clipping + calibrated Gaussian noise."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0, sigma=1.0,
                 parameters=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._clip, self._batch, self._sigma = clip, batch_size, sigma

    def init_state(self, param):
        return {}

    def update(self, param, grad, state, *, lr, step, weight_decay):
        import paddle_tpu.core.rng as _rng

        norm = jnp.sqrt(jnp.sum(jnp.square(grad)))
        g = grad * jnp.minimum(1.0, self._clip / jnp.maximum(norm, 1e-10))
        noise = self._clip * self._sigma * jax.random.normal(
            _rng.next_key(), g.shape, g.dtype
        )
        return param - lr * (g + noise / self._batch), state
