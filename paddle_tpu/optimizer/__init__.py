"""``paddle_tpu.optimizer`` (reference ``python/paddle/optimizer``)."""

from paddle_tpu.optimizer import lr  # noqa: F401
from paddle_tpu.optimizer.optimizer import Optimizer  # noqa: F401
from paddle_tpu.optimizer.optimizers import (  # noqa: F401
    ASGD,
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    DecayedAdagrad,
    Dpsgd,
    Ftrl,
    Lamb,
    Momentum,
    NAdam,
    RAdam,
    RMSProp,
    Rprop,
)
