"""LR schedulers (reference ``python/paddle/optimizer/lr.py``, ~20 schedulers).

Paddle semantics: scheduler holds ``last_epoch``; user calls ``scheduler.step()``
(per epoch or per step); optimizer reads ``scheduler()`` each update.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

__all__ = [
    "LRScheduler",
    "NoamDecay",
    "ExponentialDecay",
    "NaturalExpDecay",
    "InverseTimeDecay",
    "PolynomialDecay",
    "PiecewiseDecay",
    "CosineAnnealingDecay",
    "LinearWarmup",
    "StepDecay",
    "MultiStepDecay",
    "LambdaDecay",
    "MultiplicativeDecay",
    "ReduceOnPlateau",
    "OneCycleLR",
    "CyclicLR",
    "CosineAnnealingWarmRestarts",
    "LinearLR",
]


class LRScheduler:
    auto_step = False

    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1, verbose: bool = False) -> None:
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self) -> float:
        return self.last_lr

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch: Optional[int] = None) -> None:
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_") and not callable(v)}

    def set_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.__dict__.update(state_dict)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model: int, warmup_steps: int, learning_rate: float = 1.0, last_epoch: int = -1, verbose: bool = False) -> None:
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        step = max(self.last_epoch, 1)
        return (
            self.base_lr
            * self.d_model**-0.5
            * min(step**-0.5, step * self.warmup_steps**-1.5)
        )


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * self.gamma**self.last_epoch


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate: float, decay_steps: int, end_lr: float = 0.0001, power: float = 1.0, cycle: bool = False, last_epoch: int = -1, verbose: bool = False) -> None:
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * max(div, 1)
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * (1 - step / decay_steps) ** self.power + self.end_lr


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float], last_epoch: int = -1, verbose: bool = False) -> None:
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self) -> float:
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate: float, T_max: int, eta_min: float = 0.0, last_epoch: int = -1, verbose: bool = False) -> None:  # noqa: N803
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return (
            self.eta_min
            + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2
        )


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate: Union[float, LRScheduler], warmup_steps: int, start_lr: float, end_lr: float, last_epoch: int = -1, verbose: bool = False) -> None:
        self.lr_after = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr if isinstance(learning_rate, float) else learning_rate.base_lr, last_epoch, verbose)

    def get_lr(self) -> float:
        if self.last_epoch < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * self.last_epoch / max(self.warmup_steps, 1)
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr_after.get_lr()
        return float(self.lr_after)

    def state_dict(self) -> Dict[str, Any]:
        sd = {k: v for k, v in self.__dict__.items() if k != "lr_after"}
        if isinstance(self.lr_after, LRScheduler):
            sd["lr_after"] = self.lr_after.state_dict()
        else:
            sd["lr_after"] = self.lr_after
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]) -> None:
        inner = state_dict.pop("lr_after", None)
        self.__dict__.update(state_dict)
        if isinstance(inner, dict) and isinstance(self.lr_after, LRScheduler):
            self.lr_after.set_state_dict(inner)
        elif inner is not None:
            self.lr_after = inner


class StepDecay(LRScheduler):
    def __init__(self, learning_rate: float, step_size: int, gamma: float = 0.1, last_epoch: int = -1, verbose: bool = False) -> None:
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate: float, milestones: Sequence[int], gamma: float = 0.1, last_epoch: int = -1, verbose: bool = False) -> None:
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma**n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate: float, lr_lambda: Callable[[int], float], last_epoch: int = -1, verbose: bool = False) -> None:
        self._lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * self._lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate: float, lr_lambda: Callable[[int], float], last_epoch: int = -1, verbose: bool = False) -> None:
        self._lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        lr = self.base_lr
        for e in range(1, self.last_epoch + 1):
            lr *= self._lr_lambda(e)
        return lr


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate: float, mode: str = "min", factor: float = 0.1, patience: int = 10, threshold: float = 1e-4, threshold_mode: str = "rel", cooldown: int = 0, min_lr: float = 0, epsilon: float = 1e-8, verbose: bool = False) -> None:
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best: Optional[float] = None
        self.num_bad_epochs = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self) -> float:
        return self.last_lr

    def step(self, metrics: Any = None, epoch: Optional[int] = None) -> None:
        if metrics is None:
            return
        current = float(metrics)
        self.last_epoch += 1
        if self.best is None:
            self.best = current
            return
        if self._is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def _is_better(self, a: float, best: float) -> bool:
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < best * (1 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1 + self.threshold)
        return a > best + self.threshold


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate: float, total_steps: int, divide_factor: float = 25.0, end_learning_rate: float = 0.0001, phase_pct: float = 0.3, anneal_strategy: str = "cos", three_phase: bool = False, last_epoch: int = -1, verbose: bool = False) -> None:
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, start: float, end: float, pct: float) -> float:
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return start + (end - start) * pct

    def get_lr(self) -> float:
        step = min(self.last_epoch, self.total_steps)
        up_steps = int(self.phase_pct * self.total_steps)
        if step <= up_steps:
            return self._interp(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        return self._interp(self.max_lr, self.end_lr, (step - up_steps) / max(self.total_steps - up_steps, 1))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate: float, max_learning_rate: float, step_size_up: int, step_size_down: Optional[int] = None, mode: str = "triangular", exp_gamma: float = 1.0, scale_fn: Optional[Callable] = None, scale_mode: str = "cycle", last_epoch: int = -1, verbose: bool = False) -> None:
        self.max_lr = max_learning_rate
        self.step_size_up = step_size_up
        self.step_size_down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        self._scale_fn = scale_fn
        self.scale_mode = scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        total = self.step_size_up + self.step_size_down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        if x <= self.step_size_up:
            pct = x / self.step_size_up
        else:
            pct = 1 - (x - self.step_size_up) / self.step_size_down
        amp = (self.max_lr - self.base_lr) * pct
        if self._scale_fn is not None:
            scale_arg = cycle if self.scale_mode == "cycle" else self.last_epoch
            return self.base_lr + amp * self._scale_fn(scale_arg)
        if self.mode == "triangular2":
            return self.base_lr + amp / (2 ** (cycle - 1))
        if self.mode == "exp_range":
            return self.base_lr + amp * (self.exp_gamma**self.last_epoch)
        return self.base_lr + amp


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate: float, T_0: int, T_mult: int = 1, eta_min: float = 0.0, last_epoch: int = -1, verbose: bool = False) -> None:  # noqa: N803
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        t = self.last_epoch
        t_i = self.T_0
        while t >= t_i:
            t -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * t / t_i)) / 2


class LinearLR(LRScheduler):
    def __init__(self, learning_rate: float, total_steps: int, start_factor: float = 1.0 / 3, end_factor: float = 1.0, last_epoch: int = -1, verbose: bool = False) -> None:
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        pct = min(self.last_epoch / self.total_steps, 1.0)
        factor = self.start_factor + (self.end_factor - self.start_factor) * pct
        return self.base_lr * factor
