"""``paddle_tpu.io``: datasets + DataLoader (reference ``python/paddle/io``)."""

from paddle_tpu.io.dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from paddle_tpu.io.sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from paddle_tpu.io.dataloader import DataLoader, default_collate_fn  # noqa: F401
from paddle_tpu.io.worker import WorkerInfo, get_worker_info  # noqa: F401
