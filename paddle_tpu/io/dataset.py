"""Dataset types (reference ``python/paddle/io/dataloader/dataset.py``)."""

from __future__ import annotations

import bisect
from typing import Any, Iterable, Iterator, List, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self) -> Iterator[Any]:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Any:
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self) -> int:
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Any]) -> None:
        self.tensors = tensors

    def __getitem__(self, idx: int) -> tuple:
        return tuple(t[idx] for t in self.tensors)

    def __len__(self) -> int:
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]) -> None:
        self.datasets = list(datasets)

    def __getitem__(self, idx: int) -> tuple:
        out: List[Any] = []
        for ds in self.datasets:
            item = ds[idx]
            if isinstance(item, tuple):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)

    def __len__(self) -> int:
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]) -> None:
        self.datasets = list(datasets)

    def __iter__(self) -> Iterator[Any]:
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]) -> None:
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self) -> int:
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx: int) -> Any:
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        sample_idx = idx if ds_idx == 0 else idx - self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][sample_idx]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]) -> None:
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx: int) -> Any:
        return self.dataset[self.indices[idx]]

    def __len__(self) -> int:
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[Any], generator: Any = None) -> List[Subset]:
    lengths = list(lengths)
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(n * frac)) for frac in lengths]
        counts[-1] = n - sum(counts[:-1])
        lengths = counts
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(n)
    out, offset = [], 0
    for l in lengths:  # noqa: E741
        out.append(Subset(dataset, perm[offset : offset + l].tolist()))
        offset += l
    return out
