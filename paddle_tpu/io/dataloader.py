"""DataLoader (reference ``python/paddle/io/dataloader/dataloader_iter.py``).

Single-process and thread-prefetching loaders. The reference uses
multiprocess workers feeding a blocking queue; on TPU the host→device copy
overlaps with compute via PJRT async transfers, so a prefetch thread pool
covers the same ground without fork-safety issues inside the PJRT client.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io.dataset import Dataset, IterableDataset
from paddle_tpu.io.sampler import BatchSampler


def default_collate_fn(batch: Sequence[Any]) -> Any:
    """Stack samples into batch arrays (reference ``collate.py``)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(
        self,
        dataset: Dataset,
        feed_list: Any = None,
        places: Any = None,
        return_list: bool = True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        use_buffer_reader: bool = True,
        prefetch_factor: int = 2,
        use_shared_memory: bool = True,
        timeout: int = 0,
        worker_init_fn: Optional[Callable] = None,
        persistent_workers: bool = False,
    ) -> None:
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._user_collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._pool = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self) -> int:
        if self._iterable_mode:
            raise TypeError("IterableDataset-backed DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self) -> Iterator[Any]:
        if self._iterable_mode:
            batch: List[Any] = []
            for sample in self.dataset:  # type: ignore[arg-type]
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _wrap_np_tree(self, tree: Any) -> Any:
        """Parent-side: numpy tree from the workers → Tensor tree (the one
        host→device copy, overlapped with compute by PJRT)."""
        if isinstance(tree, np.ndarray):
            return Tensor(tree)
        if isinstance(tree, (list, tuple)):
            return type(tree)(self._wrap_np_tree(t) for t in tree)
        if isinstance(tree, dict):
            return {k: self._wrap_np_tree(v) for k, v in tree.items()}
        return tree

    def _get_pool(self):
        from paddle_tpu.io.worker import WorkerPool

        # iterable workers consume their stream; a pool can't be reused across
        # epochs in that mode
        if self._pool is not None and not self._iterable_mode and self._pool.alive():
            return self._pool
        self._pool = WorkerPool(
            self.dataset,
            self._iterable_mode,
            self.num_workers,
            self._user_collate_fn,
            self.worker_init_fn,
            self.use_shared_memory,
            float(self.timeout),
            drop_last=getattr(self, "drop_last", False),
        )
        return self._pool

    def _iter_threaded(self) -> Iterator[Any]:
        """Parent-side prefetch thread: used when a custom collate_fn is set —
        user collate functions may build framework Tensors, which must never
        run in a forked child (PJRT after fork is undefined behavior)."""
        import queue
        import threading

        q: "queue.Queue[Any]" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        error_box: List[BaseException] = []

        def producer() -> None:
            try:
                for batch in self._iter_batches():
                    q.put(batch)
            except BaseException as e:  # noqa: BLE001 - ferried to the consumer thread, re-raised there
                error_box.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        if error_box:
            raise error_box[0]

    def __iter__(self) -> Iterator[Any]:
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        if self._user_collate_fn is not None:
            yield from self._iter_threaded()
            return
        # Multiprocess workers (reference worker.py): fork pool + shared-memory
        # handoff; results re-ordered to match num_workers=0 iteration order.
        import itertools

        pool = self._get_pool()
        if self._iterable_mode:
            tasks: Iterator[Any] = ((i, self.batch_size) for i in itertools.count())
        else:
            tasks = ((i, idx) for i, idx in enumerate(self.batch_sampler))
        prefetch = self.num_workers * self.prefetch_factor
        completed = False
        try:
            for np_batch in pool.run_epoch(tasks, prefetch):
                yield self._wrap_np_tree(np_batch)
            completed = True
        finally:
            # a pool can only be reused when its epoch drained fully: breaking
            # mid-epoch leaves in-flight results that would corrupt the next
            # epoch's ordering, so tear it down
            if self._iterable_mode or not self.persistent_workers or not completed:
                pool.shutdown()
                self._pool = None

    def __del__(self) -> None:
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.shutdown()
            # analysis: disable=EH402 __del__ during interpreter teardown; queues/processes may be half-destroyed
            except Exception:  # noqa: BLE001
                pass
