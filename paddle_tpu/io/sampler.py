"""Samplers (reference ``python/paddle/io/dataloader/batch_sampler.py`` +
``sampler.py``; ``DistributedBatchSampler`` shards indices per rank)."""

from __future__ import annotations

import math
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


class Sampler:
    def __init__(self, data_source: Any = None) -> None:
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self) -> Iterator[int]:
        return iter(range(len(self.data_source)))

    def __len__(self) -> int:
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source: Any, replacement: bool = False, num_samples: Optional[int] = None, generator: Any = None) -> None:
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self) -> int:
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self) -> Iterator[int]:
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[: self.num_samples].tolist()

    def __len__(self) -> int:
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int]) -> None:
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self) -> Iterator[int]:
        yield from (self.indices[i] for i in np.random.permutation(len(self.indices)))

    def __len__(self) -> int:
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int, replacement: bool = True) -> None:
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self) -> Iterator[int]:
        p = self.weights / self.weights.sum()
        yield from np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        ).tolist()

    def __len__(self) -> int:
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(
        self,
        dataset: Any = None,
        sampler: Optional[Sampler] = None,
        shuffle: bool = False,
        batch_size: int = 1,
        drop_last: bool = False,
    ) -> None:
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self) -> Iterator[List[int]]:
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shard sample indices across data-parallel ranks (reference
    ``python/paddle/io/dataloader/batch_sampler.py`` DistributedBatchSampler)."""

    def __init__(
        self,
        dataset: Any,
        batch_size: int,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = False,
        drop_last: bool = False,
    ) -> None:
        from paddle_tpu import distributed as dist

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self) -> Iterator[List[int]]:
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        indices += indices[: (self.total_size - n)]
        local = indices[self.local_rank : self.total_size : self.nranks]
        batch: List[int] = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self) -> int:
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
