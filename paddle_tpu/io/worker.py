"""Multiprocess DataLoader workers with shared-memory handoff.

Reference: ``python/paddle/io/dataloader/worker.py`` (fork workers running
``_worker_loop`` over an index queue) + its shared-memory ``LoDTensor``
conversion. TPU-native constraints shape the redesign:

- **Workers never touch jax/PJRT.** A forked child inheriting the PJRT client
  must not use it (undefined behavior); workers collate to *numpy* trees only.
  The parent wraps results into Tensors (one host→device copy, which PJRT
  overlaps with compute).
- **Shared-memory handoff**: each ndarray in the collated tree is copied into
  a ``multiprocessing.shared_memory`` block in the worker; the parent maps it,
  wraps it, and unlinks — the batch crosses the process boundary without
  pickling the payload bytes through a pipe.
- Ordering: a single task queue feeds all workers; the parent reorders
  completed batches by index so iteration order matches num_workers=0.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as _queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["WorkerInfo", "get_worker_info"]

_worker_info: Optional["WorkerInfo"] = None


@dataclass
class WorkerInfo:
    """Reference ``worker.py`` WorkerInfo: id/num_workers/dataset, readable
    from inside ``__getitem__``/``__iter__`` for per-worker sharding."""

    id: int
    num_workers: int
    seed: int
    dataset: Any


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process: that worker's info; None in the main process."""
    return _worker_info


def np_collate(batch: Sequence[Any]) -> Any:
    """Numpy-only collate (workers must not construct jax arrays)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(np_collate(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    # Tensor-like (has .numpy()) without importing the framework in the child
    if hasattr(sample, "numpy"):
        return np.stack([np.asarray(s.numpy()) for s in batch])
    return np.asarray(batch)


# ---------------------------------------------------------------------------
# shared-memory tree transport
# ---------------------------------------------------------------------------


def _tree_to_shm(tree: Any, segments: List[Any]) -> Any:
    """Replace ndarrays in the tree with shared-memory descriptors."""
    from multiprocessing import shared_memory

    if isinstance(tree, np.ndarray):
        if tree.nbytes == 0:
            return ("__nd_inline__", tree)
        shm = shared_memory.SharedMemory(create=True, size=tree.nbytes)
        view = np.ndarray(tree.shape, tree.dtype, buffer=shm.buf)
        view[...] = tree
        segments.append(shm)
        return ("__nd_shm__", shm.name, tree.shape, str(tree.dtype))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_to_shm(t, segments) for t in tree)
    if isinstance(tree, dict):
        return {k: _tree_to_shm(v, segments) for k, v in tree.items()}
    return tree


def _tree_from_shm(tree: Any) -> Any:
    """Parent side: map descriptors back to ndarrays (copy + unlink)."""
    from multiprocessing import shared_memory

    if isinstance(tree, tuple) and tree and tree[0] == "__nd_inline__":
        return tree[1]
    if isinstance(tree, tuple) and tree and tree[0] == "__nd_shm__":
        _, name, shape, dtype = tree
        shm = shared_memory.SharedMemory(name=name)
        try:
            # copy out: the Tensor wrap would otherwise hold freed shm memory
            arr = np.ndarray(shape, dtype, buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        return arr
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_from_shm(t) for t in tree)
    if isinstance(tree, dict):
        return {k: _tree_from_shm(v) for k, v in tree.items()}
    return tree


# ---------------------------------------------------------------------------
# worker loop
# ---------------------------------------------------------------------------

_SHUTDOWN = "__shutdown__"


def _worker_loop(
    dataset: Any,
    iterable_mode: bool,
    task_q: Any,
    result_q: Any,
    collate_fn: Optional[Callable],
    worker_init_fn: Optional[Callable],
    worker_id: int,
    num_workers: int,
    base_seed: int,
    use_shared_memory: bool,
    drop_last: bool,
    ring: Any = None,
) -> None:
    global _worker_info
    _worker_info = WorkerInfo(
        id=worker_id, num_workers=num_workers, seed=base_seed + worker_id, dataset=dataset
    )
    np.random.seed((base_seed + worker_id) % (2**31))
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        collate = collate_fn or np_collate
        if iterable_mode:
            # each worker walks its stride of the stream (reference leaves the
            # split to the user via WorkerInfo; the stride default means
            # num_workers>0 on an IterableDataset never duplicates samples)
            it = itertools.islice(iter(dataset), worker_id, None, num_workers)
            for batch_idx in itertools.count():
                task = task_q.get()
                if task == _SHUTDOWN:
                    return
                bs = task[1]
                batch = list(itertools.islice(it, bs))
                if not batch or (drop_last and len(batch) < bs):
                    result_q.put((task[0], "__end__", None))
                    return
                out = collate(batch)
                _send(result_q, task[0], out, use_shared_memory, ring)
        else:
            while True:
                task = task_q.get()
                if task == _SHUTDOWN:
                    return
                batch_idx, indices = task
                out = collate([dataset[i] for i in indices])
                _send(result_q, batch_idx, out, use_shared_memory, ring)
    except KeyboardInterrupt:
        pass
    except BaseException as exc:  # noqa: BLE001 - surface in parent
        import traceback

        result_q.put((-1, "__error__", f"{type(exc).__name__}: {exc}\n{traceback.format_exc()}"))


def _send(result_q: Any, batch_idx: int, out: Any, use_shared_memory: bool,
          ring: Any = None) -> None:
    if ring is not None:
        # native ring arena: slots are reused, no per-batch segment
        # create/unlink churn; oversized batches fall through to the
        # per-segment path below
        import pickle

        payload = pickle.dumps(out, protocol=4)
        # finite timeout: a full ring with a stopped parent must not trap the
        # worker in the C spin loop — fall through to the per-segment path
        if len(payload) <= ring.slot_bytes and ring.put(
            payload, tag=batch_idx, timeout=5.0
        ):
            result_q.put((batch_idx, "__ring__", None))
            return
    if use_shared_memory:
        segments: List[Any] = []
        desc = _tree_to_shm(out, segments)
        result_q.put((batch_idx, "__shm__", desc))
        # the parent unlinks; worker only closes its mapping
        for shm in segments:
            shm.close()
    else:
        result_q.put((batch_idx, "__data__", out))


# ---------------------------------------------------------------------------
# parent-side pool
# ---------------------------------------------------------------------------


class WorkerPool:
    """Fork-based worker pool streaming ordered batches to the parent."""

    def __init__(
        self,
        dataset: Any,
        iterable_mode: bool,
        num_workers: int,
        collate_np: Optional[Callable],
        worker_init_fn: Optional[Callable],
        use_shared_memory: bool,
        timeout: float,
        drop_last: bool = False,
    ) -> None:
        ctx = mp.get_context("fork") if "fork" in mp.get_all_start_methods() else mp.get_context()
        self._ctx = ctx
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._num_workers = num_workers
        self._timeout = timeout
        self._iterable = iterable_mode
        # native shared-memory ring (cpp/shm_ring.cpp): slot reuse instead of
        # per-batch segment create/unlink; fork inherits the mapping. Python
        # shared_memory stays as the fallback (ring absent / oversized batch).
        self._ring = None
        if use_shared_memory and ctx.get_start_method() == "fork":
            try:
                import os as _os

                from paddle_tpu_native.shm_ring import ShmRing, available

                if available():
                    import time as _time

                    slot_bytes = int(
                        _os.environ.get("PADDLE_SHM_RING_SLOT_BYTES", str(8 << 20))
                    )
                    self._ring = ShmRing(
                        f"/pt_dl_{_os.getpid()}_{int(_time.monotonic() * 1e6) & 0xFFFFFF}",
                        nslots=max(4, num_workers * 2),
                        slot_bytes=slot_bytes,
                        create=True,
                    )
            except Exception:  # noqa: BLE001 - fallback transport covers it
                self._ring = None
        self._ring_buf: Dict[int, Any] = {}
        base_seed = int(np.random.randint(0, 2**31 - 1))
        self._procs = [
            ctx.Process(
                target=_worker_loop,
                args=(
                    dataset, iterable_mode, self._task_q, self._result_q,
                    collate_np, worker_init_fn, wid, num_workers, base_seed,
                    use_shared_memory, drop_last, self._ring,
                ),
                daemon=True,
            )
            for wid in range(num_workers)
        ]
        for p in self._procs:
            p.start()

    def run_epoch(self, tasks: Iterator[Tuple[int, Any]], prefetch: int) -> Iterator[Any]:
        """Feed tasks, yield results in batch-index order."""
        buf: Dict[int, Any] = {}
        next_idx = 0
        inflight = 0
        ended_workers = 0
        tasks = iter(tasks)
        exhausted = False

        def feed() -> None:
            nonlocal inflight, exhausted
            while not exhausted and inflight < prefetch:
                try:
                    self._task_q.put(next(tasks))
                    inflight += 1
                except StopIteration:
                    exhausted = True

        feed()
        while inflight > 0:
            try:
                idx, kind, payload = self._result_q.get(
                    timeout=self._timeout if self._timeout > 0 else None
                )
            except _queue.Empty:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker timed out after {self._timeout}s"
                ) from None
            if kind == "__error__":
                self.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{payload}")
            inflight -= 1
            if kind == "__end__":
                # an iterable-mode worker ran dry
                ended_workers += 1
                if ended_workers >= self._num_workers:
                    break  # queued tasks have no worker left to serve them
                feed()
                continue
            if kind == "__ring__":
                import pickle

                while idx not in self._ring_buf:
                    got = self._ring.get(timeout=self._timeout if self._timeout > 0 else -1.0)
                    if got is None:
                        self.shutdown()
                        raise RuntimeError("shm ring read timed out")
                    blob, tag = got
                    self._ring_buf[tag] = pickle.loads(blob)
                data = self._ring_buf.pop(idx)
            elif kind == "__shm__":
                data = _tree_from_shm(payload)
            else:
                data = payload
            buf[idx] = data
            feed()
            while next_idx in buf:
                yield buf.pop(next_idx)
                next_idx += 1
        # drain any ordered leftovers (iterable mode may complete out of order)
        for idx in sorted(buf):
            yield buf[idx]

    def alive(self) -> bool:
        return any(p.is_alive() for p in self._procs)

    def shutdown(self) -> None:
        for _ in self._procs:
            try:
                self._task_q.put(_SHUTDOWN)
            except Exception:  # noqa: BLE001 - queue already closed; survivors are terminated below
                break
        for p in self._procs:
            p.join(timeout=2.0)
            if p.is_alive():
                p.terminate()
        # drain abandoned results so their shared-memory segments are unlinked
        # (an epoch broken mid-iteration leaves payloads in the queue)
        while True:
            try:
                _idx, kind, payload = self._result_q.get_nowait()
            except Exception:  # noqa: BLE001 - Empty or closed
                break
            if kind == "__shm__":
                try:
                    _tree_from_shm(payload)
                # analysis: disable=EH402 drain is best-effort; the segment may already be unlinked by its consumer
                except Exception:  # noqa: BLE001
                    pass
        for q in (self._task_q, self._result_q):
            q.cancel_join_thread()
            q.close()
        if self._ring is not None:
            try:
                self._ring.close()
            # analysis: disable=EH402 shutdown path; ring segment may already be unlinked by the OS or a dead worker
            except Exception:  # noqa: BLE001
                pass
            self._ring = None
