"""``python -m paddle_tpu.distributed.launch`` — the job launcher.

Reference: ``python/paddle/distributed/launch/`` (``main.py:23``, collective
controller, master rendezvous, watcher).
"""

from paddle_tpu.distributed.launch.main import launch, main  # noqa: F401
