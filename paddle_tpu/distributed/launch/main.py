"""Launcher: spawn training processes with rendezvous env wiring.

Reference: ``python/paddle/distributed/launch/main.py:23`` + the collective
controller (``controllers/collective.py``) and HTTP/ETCD master
(``controllers/master.py``).

TPU-native model: single-controller SPMD — ONE process per HOST drives all
local chips (the reference spawns one per GPU). So:

- single-node: run the script once with the bootstrap env set (optionally
  N virtual processes for CPU-backend testing via
  ``--nproc_per_node`` > 1, each pinned to a subset via JAX flags).
- multi-node: per node, set ``PADDLE_MASTER`` (the jax.distributed
  coordination service address — the TCPStore/ETCD-master analog),
  ``PADDLE_NNODES``, ``PADDLE_TRAINER_ID``; ``init_parallel_env`` then wires
  ``jax.distributed.initialize`` from these.

Failure watching (reference ``watcher.py``): the launcher polls children and
tears the job down when any exits nonzero — the elastic manager's restart
hook point.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["launch", "main"]


def _parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a (multi-host) SPMD training job",
    )
    p.add_argument("--master", default=None, help="coordinator host:port (multi-node)")
    p.add_argument("--nnodes", type=int, default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", "--node_rank", type=int, dest="rank",
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 for TPU SPMD; >1 for CPU testing)")
    p.add_argument("--devices", "--gpus", default=None, dest="devices",
                   help="visible device ids (comma separated)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default="collective", choices=["collective"])
    p.add_argument(
        "--max_restarts", type=int, default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTARTS", "0")),
        help="elastic fault tolerance: relaunch a failed worker up to N times "
        "(reference elastic manager relaunch, manager.py:251); the child sees "
        "PADDLE_RESTART_COUNT and should resume from its latest checkpoint",
    )
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args: argparse.Namespace, local_rank: int) -> Dict[str, str]:
    env = dict(os.environ)
    world = args.nnodes * args.nproc_per_node
    global_rank = args.rank * args.nproc_per_node + local_rank
    env["PADDLE_TRAINER_ID"] = str(global_rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    env["PADDLE_NNODES"] = str(args.nnodes)
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env["MASTER_ADDR"] = args.master.split(":")[0]
        env["MASTER_PORT"] = args.master.split(":")[-1]
    if args.devices:
        env["TPU_VISIBLE_DEVICES"] = args.devices
        env["CUDA_VISIBLE_DEVICES"] = args.devices  # harmless off-GPU
    return env


def launch(argv: Optional[List[str]] = None) -> int:
    args = _parse_args(argv)
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    def spawn(local_rank: int, restart_count: int = 0) -> subprocess.Popen:
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        stdout = None
        if args.log_dir:
            log_path = os.path.join(args.log_dir, f"workerlog.{local_rank}")
            stdout = open(log_path, "a" if restart_count else "w")
        env = _child_env(args, local_rank)
        env["PADDLE_RESTART_COUNT"] = str(restart_count)
        proc = subprocess.Popen(
            cmd, env=env, stdout=stdout, stderr=subprocess.STDOUT if stdout else None
        )
        proc._local_rank = local_rank  # type: ignore[attr-defined]
        proc._log = stdout  # type: ignore[attr-defined]
        return proc

    def reap(p: subprocess.Popen) -> None:
        if getattr(p, "_log", None) is not None:
            p._log.close()  # type: ignore[attr-defined]

    def terminate_all(procs: List[subprocess.Popen]) -> None:
        for other in procs:
            other.send_signal(signal.SIGTERM)
        for other in procs:
            try:
                other.wait(timeout=10)
            except subprocess.TimeoutExpired:
                other.kill()
            reap(other)

    restart_count = 0
    procs: List[subprocess.Popen] = [spawn(r) for r in range(args.nproc_per_node)]

    # watcher (reference watcher.py): poll children; on failure either
    # relaunch (elastic fault tolerance, --max_restarts) or tear the job
    # down. A relaunch restarts the WHOLE local group — surviving ranks are
    # blocked inside collectives waiting on the dead one and a lone fresh
    # process could never rejoin the advanced coordination state (the
    # reference elastic manager also relaunches all local trainers).
    rc = 0
    try:
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                reap(p)
                if ret == 0:
                    continue
                if restart_count < args.max_restarts:
                    restart_count += 1
                    sys.stderr.write(
                        f"[launch] worker {p._local_rank} exited rc={ret}; "  # type: ignore[attr-defined]
                        f"restarting the local group "
                        f"(restart {restart_count}/{args.max_restarts})\n"
                    )
                    terminate_all(procs)
                    procs = [spawn(r, restart_count) for r in range(args.nproc_per_node)]
                    break
                rc = ret
                terminate_all(procs)
                procs = []
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            reap(p)
    return rc


def main() -> None:
    sys.exit(launch())
