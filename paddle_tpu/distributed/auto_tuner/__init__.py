"""Distributed-configuration auto-tuner.

Reference: ``python/paddle/distributed/auto_tuner/tuner.py:21`` (AutoTuner:
grid search over (dp, mp, pp, sharding, micro-batch, recompute), pruned by
divisibility + memory estimates, launching one trial per config and ranking
by the measured metric).

TPU-native reshape: a "trial" is not a relaunched process — SPMD means one
process can rebuild the mesh and jit the train step per candidate, so
``Tuner.run`` drives ``trial_fn(cfg) -> metric`` directly (raise ``MemoryError``
/ any exception to mark the config failed, exactly how the reference marks
OOM trials). The memory prune uses an analytic HBM model: params/grads/
optimizer-state bytes divided by the sharding/mp/pp factors plus an
activation term scaled by micro-batch and recompute.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["AutoTuner", "Tuner", "default_candidates", "prune_by_memory", "divisor"]


def divisor(num: int, reverse: bool = False) -> List[int]:
    """All divisors of ``num`` (reference ``utils.py:32``)."""
    out = [d for d in range(1, num + 1) if num % d == 0]
    return out[::-1] if reverse else out


def default_candidates(tuner_cfg: Dict[str, Any]) -> Dict[str, List[Any]]:
    """Candidate value lists per axis (reference ``utils.py:162``)."""
    n = int(tuner_cfg["num_gpus"])
    model = tuner_cfg.get("model_cfg", {})
    layers = int(model.get("num_layers", 1) or 1)
    heads = int(model.get("num_attention_heads", 1) or 1)
    hidden = int(model.get("hidden_size", 1) or 1)
    vocab = int(model.get("vocab_size", 1) or 1)
    global_bs = int(tuner_cfg.get("global_batch_size", 1) or 1)

    def _axis(key: str, default: List[Any]) -> List[Any]:
        v = tuner_cfg.get(key, "auto")
        if v == "auto" or v is None:
            return default
        vals = list(v) if isinstance(v, (list, tuple)) else [v]
        return list(dict.fromkeys(vals))  # user lists may repeat; dedupe

    mp_default = [
        d for d in divisor(n)
        if heads % d == 0 and hidden % d == 0 and vocab % d == 0
    ]
    pp_default = [d for d in divisor(n) if layers % d == 0]
    return {
        "mp_degree": _axis("mp_degree", mp_default),
        "pp_degree": _axis("pp_degree", pp_default),
        "sharding_degree": _axis("sharding_degree", divisor(n)),
        "sharding_stage": _axis("sharding_stage", [1, 2, 3]),
        "micro_batch_size": _axis("micro_batch_size", divisor(global_bs)),
        "use_recompute": _axis("use_recompute", [True, False]),
    }


def _model_bytes(model: Dict[str, Any]) -> float:
    layers = int(model.get("num_layers", 0) or 0)
    hidden = int(model.get("hidden_size", 0) or 0)
    vocab = int(model.get("vocab_size", 0) or 0)
    inter = int(model.get("intermediate_size", 4 * hidden) or 4 * hidden)
    if not layers or not hidden:
        return 0.0
    per_layer = 4 * hidden * hidden + 3 * hidden * inter  # attn + glu mlp
    return float(layers * per_layer + 2 * vocab * hidden)


def prune_by_memory(cfg: Dict[str, Any], tuner_cfg: Dict[str, Any]) -> bool:
    """True when the config is estimated to exceed per-chip HBM (reference
    ``prune.py`` prune_by_memory_estimation). Analytic model:

    - weights bf16 + fp32 master + AdamW moments: 2 + 4 + 8 = 14 B/param,
      divided by mp*pp, with master+moments further divided by sharding
      (stage >= 1 shards optimizer state; stage >= 2 also grads: 4 B).
    - activations: micro_bs * seq * hidden * layers/pp * ~16 B (bf16,
      attn+mlp residual stream), /sqrt(1) or a flat /5 with recompute.
    """
    hbm = float(tuner_cfg.get("hbm_bytes", 16e9))
    model = tuner_cfg.get("model_cfg", {})
    n_param = _model_bytes(model)
    if not n_param:
        return False
    mp = int(cfg.get("mp_degree", 1))
    pp = int(cfg.get("pp_degree", 1))
    shard = max(1, int(cfg.get("sharding_degree", 1)))
    stage = int(cfg.get("sharding_stage", 1))
    mbs = int(cfg.get("micro_batch_size", 1))
    seq = int(model.get("seq_length", 2048) or 2048)
    hidden = int(model.get("hidden_size", 1) or 1)
    layers = int(model.get("num_layers", 1) or 1)

    shard_params = n_param / (mp * pp)
    weights = 2.0 * shard_params / (shard if stage >= 3 else 1)
    grads = 4.0 * shard_params / (shard if stage >= 2 else 1)
    opt_state = 12.0 * shard_params / shard  # master + two moments, fp32
    act_per_layer = 16.0 * mbs * seq * hidden
    act = act_per_layer * (layers / pp)
    if cfg.get("use_recompute", False):
        act = act_per_layer + act / layers  # boundary activations only
    return (weights + grads + opt_state + act) > hbm


class AutoTuner:
    """Grid search over pruned parallel configs (reference ``tuner.py:21``)."""

    def __init__(self, tuner_cfg: Dict[str, Any]) -> None:
        self.tuner_cfg = dict(tuner_cfg)
        self.num_gpus = int(tuner_cfg["num_gpus"])
        self.task_limit = int(tuner_cfg.get("task_limit", 100))
        self.metric_mode = tuner_cfg.get("mode", "max")  # max: throughput
        self.cur_task_id = 0
        self.history_cfgs: List[Dict[str, Any]] = []
        self._queue = self._build_queue()

    # -- candidate enumeration ----------------------------------------------
    def _build_queue(self) -> List[Dict[str, Any]]:
        cand = default_candidates(self.tuner_cfg)
        out: List[Dict[str, Any]] = []
        for mp, pp, sd, st, mbs, rc in itertools.product(
            cand["mp_degree"],
            cand["pp_degree"],
            cand["sharding_degree"],
            cand["sharding_stage"],
            cand["micro_batch_size"],
            cand["use_recompute"],
        ):
            if mp * pp > self.num_gpus or self.num_gpus % (mp * pp) != 0:
                continue
            dp = self.num_gpus // (mp * pp)
            if sd > dp or dp % sd != 0:
                continue  # sharding group lives inside dp
            if sd == 1 and st != 1:
                continue  # stages only differ with a real sharding group
            gbs = int(self.tuner_cfg.get("global_batch_size", 1) or 1)
            if gbs % dp != 0 or (gbs // dp) % mbs != 0:
                continue
            cfg = {
                "dp_degree": dp,
                "mp_degree": mp,
                "pp_degree": pp,
                "sharding_degree": sd,
                "sharding_stage": st,
                "micro_batch_size": mbs,
                "use_recompute": rc,
                "acc_steps": (gbs // dp) // mbs,
            }
            if prune_by_memory(cfg, self.tuner_cfg):
                continue
            out.append(cfg)
        order = self.tuner_cfg.get("order", "memory")
        if order == "cost" and self.tuner_cfg.get("model_cfg"):
            # cost-model ordering (reference auto_parallel/static/cost/):
            # fastest-predicted configs trial first, so a truncated sweep
            # (task_limit) still covers the promising region
            from paddle_tpu.distributed.auto_parallel.cost_model import rank_configs

            return rank_configs(out, self.tuner_cfg)
        # memory-friendly first: higher parallelism degrees before plain dp
        # (the reference's memory_sort), so early trials are least likely to OOM
        out.sort(
            key=lambda c: (
                -(c["mp_degree"] * c["pp_degree"] * c["sharding_degree"]),
                c["micro_batch_size"],
            )
        )
        return out

    # -- reference surface ---------------------------------------------------
    def search_once(self) -> Optional[Dict[str, Any]]:
        """Next config to trial, or None when exhausted/limited."""
        if self.cur_task_id >= self.task_limit or not self._queue:
            return None
        self.cur_task_id += 1
        return self._queue.pop(0)

    def add_cfg(self, cfg: Dict[str, Any]) -> None:
        self.history_cfgs.append(cfg)

    def get_best_cfg(self) -> Optional[Dict[str, Any]]:
        ok = [c for c in self.history_cfgs if c.get("metric") is not None]
        if not ok:
            return None
        return (max if self.metric_mode == "max" else min)(
            ok, key=lambda c: c["metric"]
        )

    # -- TPU-native driver ---------------------------------------------------
    def run(
        self,
        trial_fn: Callable[[Dict[str, Any]], float],
        max_trials: Optional[int] = None,
        isolation: str = "none",
        trial_timeout: Optional[float] = 600.0,
    ) -> Optional[Dict[str, Any]]:
        """Trial every candidate: ``trial_fn(cfg)`` returns the metric
        (tokens/s or step time); exceptions mark the config failed (the
        reference's OOM/error trials). Returns the best config.

        ``isolation="subprocess"`` forks each trial into its own child
        (reference ``tuner.py``'s launched-trial model): an XLA OOM, Mosaic
        crash, or hang (``trial_timeout`` seconds, default 10 min — never
        None: forking a JAX-multithreaded parent can deadlock the child
        before it reports, and only the timeout recovers the sweep) kills ONE
        child and marks that trial failed instead of losing the whole sweep.
        In-process mode remains the default for CPU tests."""
        if isolation not in ("none", "subprocess"):
            raise ValueError(f"isolation must be none/subprocess, got {isolation!r}")
        trials = 0
        while max_trials is None or trials < max_trials:
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            if isolation == "subprocess":
                metric, err = _run_trial_in_subprocess(trial_fn, dict(cfg), trial_timeout)
                cfg["metric"] = metric
                cfg["status"] = "ok" if err is None else err
            else:
                try:
                    cfg["metric"] = float(trial_fn(dict(cfg)))
                    cfg["status"] = "ok"
                except Exception as exc:  # noqa: BLE001 - failed trial, keep searching
                    cfg["metric"] = None
                    cfg["status"] = f"failed: {exc}"[:200]
            self.add_cfg(cfg)
        return self.get_best_cfg()


def _run_trial_in_subprocess(
    trial_fn: Callable[[Dict[str, Any]], float],
    cfg: Dict[str, Any],
    timeout: Optional[float],
):
    """One trial in a forked child. Returns ``(metric, None)`` on success or
    ``(None, "failed: ...")`` — a hard crash (OOM kill, Mosaic abort) or a
    timeout only takes the child with it."""
    import multiprocessing as mp
    import os as _os

    ctx = mp.get_context("fork")  # closures need fork; spawn can't pickle them
    recv, send = ctx.Pipe(duplex=False)

    def child(conn, cfg):
        code = 0
        try:
            conn.send(("ok", float(trial_fn(cfg))))
        except BaseException as exc:  # noqa: BLE001 - report, then die
            code = 1
            try:
                conn.send(("err", f"{type(exc).__name__}: {exc}"[:200]))
            # analysis: disable=EH402 forked child is dying; the parent reads a closed pipe as a crash
            except Exception:  # noqa: BLE001
                pass
        conn.close()
        # analysis: disable=RB501 forked trial child owns no checkpoints or requests; the parent reads the pipe, and running jax teardown in the fork would deadlock
        _os._exit(code)  # skip atexit/jax teardown in the fork

    proc = ctx.Process(target=child, args=(send, cfg), daemon=True)
    proc.start()
    send.close()
    msg = None
    timed_out = False
    try:
        # poll(None) blocks until data or EOF, so timed_out can only be set
        # when a real timeout was given (a dying child delivers EOF, which
        # must classify as "died", not "timed out" — is_alive() races there)
        if recv.poll(timeout):
            msg = recv.recv()
        else:
            timed_out = True
    except (EOFError, OSError):
        msg = None
    finally:
        recv.close()
    if timed_out:
        proc.terminate()
        proc.join(5)
        if proc.is_alive():
            proc.kill()
            proc.join(5)
        return None, f"failed: trial timed out after {timeout}s"
    proc.join(10)
    if msg is None:
        return None, f"failed: trial process died (exitcode {proc.exitcode})"
    kind, payload = msg
    if kind == "ok":
        return payload, None
    return None, f"failed: {payload}"


Tuner = AutoTuner  # reference alias
