"""MoE communication utils: global_scatter / global_gather.

Reference: ``python/paddle/distributed/utils/moe_utils.py`` — thin wrappers
over the ``global_scatter``/``global_gather`` collective ops
(``paddle/fluid/operators/collective/global_scatter_op.cc``): tokens routed
to per-(expert, rank) buckets via all-to-all with per-rank counts.

TPU-native: inside a shard_map region these are ``lax.all_to_all`` over the
expert-parallel axis on equal-sized capacity buckets (the GSPMD lowering of
the MoE dispatch einsum). The functions below provide API parity for code
ported from the reference; new code should use ``MoELayer``'s einsum
formulation, which lets XLA fuse routing into the transfer.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.distributed.collective import Group, alltoall_single

__all__ = ["global_scatter", "global_gather"]


def _check_uniform(counts: Any, name: str) -> None:
    """The TPU lowering runs fixed-capacity buckets; uneven per-rank counts
    would silently land tokens in the wrong buckets — fail fast instead."""
    if counts is None:
        return
    import numpy as np

    vals = np.asarray(getattr(counts, "numpy", lambda: counts)())
    if vals.size and not (vals == vals.flat[0]).all():
        raise NotImplementedError(
            f"{name} requires equal-sized (capacity-padded) buckets on TPU; "
            f"got uneven counts {vals.tolist()}. Pad to capacity first or use "
            "MoELayer's einsum dispatch."
        )


def global_scatter(
    x: Any,
    local_count: Any,
    global_count: Any,
    group: Optional[Group] = None,
    use_calc_stream: bool = True,
) -> Any:
    """All-to-all token dispatch. With equal per-rank buckets this is one
    ``alltoall_single``; uneven counts must be capacity-padded first (the
    TPU formulation always runs fixed-capacity buckets)."""
    _check_uniform(local_count, "global_scatter")
    _check_uniform(global_count, "global_scatter")
    return alltoall_single(None, x, group=group)


def global_gather(
    x: Any,
    local_count: Any,
    global_count: Any,
    group: Optional[Group] = None,
    use_calc_stream: bool = True,
) -> Any:
    """Inverse of :func:`global_scatter` (returns tokens to their source
    ranks) — the same fixed-capacity all-to-all in reverse."""
    _check_uniform(local_count, "global_gather")
    _check_uniform(global_count, "global_gather")
    return alltoall_single(None, x, group=group)
