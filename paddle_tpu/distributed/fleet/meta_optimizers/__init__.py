"""Dygraph meta-optimizers (reference ``fleet/meta_optimizers/``)."""

from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer import (  # noqa: F401
    DygraphShardingOptimizer,
    DygraphShardingOptimizerV2,
    HybridParallelOptimizer,
)
