"""Hybrid-parallel optimizer wrapper.

Reference: ``fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py``
(``HybridParallelOptimizer:255``) — wraps the user optimizer so global-norm
grad clip spans the mp/pp/sharding groups, and routes to the sharding
optimizer when a sharding axis exists.

TPU-native: gradients are global-view arrays, so a global-norm clip computed
on them IS already reduced over every parallel group (GSPMD inserts the
partial-norm psum). What remains is the dispatch: wrap with the ZeRO sharded
optimizer when the topology has a sharding axis.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer import (
    DygraphShardingOptimizer,
)

__all__ = ["HybridParallelOptimizer"]


class HybridParallelOptimizer:
    def __init__(self, optimizer: Any, hcg: Any = None, strategy: Any = None) -> None:
        self._hcg = hcg
        self._strategy = strategy
        self._sharding = False
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
            optimizer = DygraphShardingOptimizer(optimizer, hcg=hcg)
            self._sharding = True
        self._inner_opt = optimizer

    def __getattr__(self, item: str) -> Any:
        return getattr(self._inner_opt, item)

    def step(self) -> None:
        self._inner_opt.step()

    def minimize(self, loss: Any, *args: Any, **kwargs: Any) -> None:
        loss.backward()
        self.step()

    def clear_grad(self, set_to_zero: bool = False) -> None:
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad
