"""ZeRO stage-1/2 sharded optimizer (optimizer-state + gradient sharding).

Reference: ``python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py`` (``DygraphShardingOptimizer:44`` — per-rank
param-group round-robin with broadcast of updated params;
``DygraphShardingOptimizerV2:571`` — reduce-scatter "stage-1 v2").

TPU-native design: the reference assigns whole parameters to ranks and
hand-codes broadcast/reduce-scatter. Here sharding is a *placement*: for the
update we reshard grad + param + optimizer state to ``Shard(dim)`` over the
``sharding`` mesh axis (XLA emits the reduce-scatter), run the (jit-fused)
update on the shard, and reshard the updated param back to its original
placement (XLA emits the all-gather). Optimizer states are created from the
sharded param so they are *born sharded* and never materialize replicated —
the ZeRO memory saving. Stage 1 vs stage 2 in GSPMD differ only in whether
the gradient buffer is also kept sharded between backward and step; both
classes produce identical numerics.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import ProcessMesh, get_mesh
from paddle_tpu.distributed.placements import Placement, Replicate, Shard

__all__ = ["DygraphShardingOptimizer", "DygraphShardingOptimizerV2"]


def _find_sharding_axis(mesh: ProcessMesh, preferred: str = "sharding") -> Optional[str]:
    if preferred in mesh.dim_names and mesh.get_dim_size(preferred) > 1:
        return preferred
    if "dp" in mesh.dim_names and mesh.get_dim_size("dp") > 1:
        return "dp"
    return None


def _current_placements(p: Tensor, mesh: ProcessMesh) -> List[Placement]:
    plc = getattr(p, "placements", None)
    if plc is not None and len(plc) == mesh.ndim:
        return list(plc)
    return [Replicate() for _ in range(mesh.ndim)]


def sharded_placements(
    p: Tensor, mesh: ProcessMesh, axis: str
) -> Optional[List[Placement]]:
    """Placements for the ZeRO shard of ``p``: its current placements with the
    sharding axis additionally assigned ``Shard(dim)`` for the first dim that
    is divisible by the axis degree and not already sharded. ``None`` when no
    dim qualifies (small params stay replicated — the reference likewise
    leaves the rank-assignment uneven for odd shapes)."""
    degree = mesh.get_dim_size(axis)
    ax_idx = mesh.dim_names.index(axis)
    plc = _current_placements(p, mesh)
    if not isinstance(plc[ax_idx], Replicate):
        return None  # axis already in use for this param
    taken = {pl.get_dim() for pl in plc if isinstance(pl, Shard)}
    for dim in range(p.ndim):
        if dim in taken:
            continue
        if p.shape[dim] % degree == 0 and p.shape[dim] >= degree:
            new = list(plc)
            new[ax_idx] = Shard(dim)
            return new
    return None


class DygraphShardingOptimizer:
    """Wrap an inner optimizer with ZeRO-sharded state/update (stage 1)."""

    _shard_grads = False  # stage 2 subclass flips this

    def __init__(
        self,
        optimizer: Any,
        hcg: Any = None,
        mesh: Optional[ProcessMesh] = None,
        axis: Optional[str] = None,
    ) -> None:
        self._inner_opt = optimizer
        if mesh is None:
            if hcg is not None:
                mesh = hcg.get_parallel_mesh()
            else:
                mesh = get_mesh()
        if mesh is None:
            raise ValueError("DygraphShardingOptimizer needs a mesh (fleet.init or dist.set_mesh)")
        self._mesh = mesh
        self._axis = axis or _find_sharding_axis(mesh)
        if self._axis is None:
            raise ValueError(
                f"mesh {mesh} has no sharding-capable axis (looked for 'sharding'/'dp' with degree > 1)"
            )
        # original (pre-ZeRO) placements to gather back to after the update
        self._orig_placements: Dict[int, List[Placement]] = {}
        self._shard_plc: Dict[int, Optional[List[Placement]]] = {}
        for p in optimizer._parameters:
            self._orig_placements[id(p)] = _current_placements(p, mesh)
            self._shard_plc[id(p)] = sharded_placements(p, mesh, self._axis)
        if self._shard_grads:
            # stage 2: reshard each gradient the moment backward produces it,
            # so grads never sit replicated between backward and step (the
            # reference's reduce-scatter point, reducer.cc hooks)
            from paddle_tpu.distributed.api import reshard

            for p in optimizer._parameters:
                plc = self._shard_plc[id(p)]
                if plc is None:
                    continue

                def _shard_grad(g: Tensor, _plc: List[Placement] = plc) -> Tensor:
                    return reshard(g, self._mesh, _plc)

                p.register_hook(_shard_grad)

    # delegate the full Optimizer surface
    def __getattr__(self, item: str) -> Any:
        return getattr(self._inner_opt, item)

    def _reshard_inplace(self, t: Tensor, placements: List[Placement]) -> None:
        from paddle_tpu.distributed.api import reshard

        import paddle_tpu

        with paddle_tpu.no_grad():
            d = reshard(t, self._mesh, placements)
        t._data = d._data
        t.process_mesh = self._mesh
        t.placements = placements

    def step(self) -> None:
        import paddle_tpu

        opt = self._inner_opt
        live = [p for p in opt._parameters if not p.stop_gradient and p.grad is not None]
        # 1. shard params + grads over the sharding axis (reduce-scatter point)
        for p in live:
            plc = self._shard_plc[id(p)]
            if plc is None:
                continue
            self._reshard_inplace(p, plc)
            self._reshard_inplace(p.grad, plc)
        # 2. sharded update — optimizer state is created from the sharded
        #    param on first use, so moments/master weights are born sharded
        opt.step()
        # 3. gather updated params back to their working placements
        for p in live:
            if self._shard_plc[id(p)] is None:
                continue
            self._reshard_inplace(p, self._orig_placements[id(p)])

    def minimize(self, loss: Tensor, *args: Any, **kwargs: Any) -> None:
        loss.backward()
        self.step()

    def clear_grad(self, set_to_zero: bool = False) -> None:
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self) -> Dict[str, Any]:
        return self._inner_opt.state_dict()

    def set_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self._inner_opt.set_state_dict(state_dict)


class DygraphShardingOptimizerV2(DygraphShardingOptimizer):
    """Stage-2 semantics (reference ``:571``): gradients live sharded from the
    moment they are reduced. Under GSPMD the reduce-scatter is emitted at the
    same point either way; numerics match stage 1."""

    _shard_grads = True
