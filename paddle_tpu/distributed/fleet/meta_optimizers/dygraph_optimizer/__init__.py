from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer import (  # noqa: F401
    DygraphShardingOptimizer,
    DygraphShardingOptimizerV2,
)
from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (  # noqa: F401
    HybridParallelOptimizer,
)
