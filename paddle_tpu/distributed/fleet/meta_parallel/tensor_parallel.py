"""TensorParallel model wrapper.

Reference: ``fleet/meta_parallel/tensor_parallel.py`` — broadcasts non-TP
parameters/buffers across the mp group at wrap time so all ranks start
identical. TPU-native: single-controller SPMD has one copy of every replicated
parameter by construction, so the wrapper only (1) places un-sharded params
replicated on the mesh and (2) shards DP inputs, mirroring DataParallel.
"""

from __future__ import annotations

from typing import Any

from paddle_tpu.nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers: Layer, hcg: Any = None, strategy: Any = None, **kwargs: Any) -> None:
        super().__init__()
        self._layers = layers
        from paddle_tpu.distributed.fleet import fleet as _fleet

        self._hcg = hcg or _fleet.get_hybrid_communicate_group()
        # place any parameter that has no sharding yet as mesh-replicated (the
        # broadcast-at-init of the reference)
        from paddle_tpu.distributed.mesh import get_mesh

        mesh = get_mesh()
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            import paddle_tpu

            with paddle_tpu.no_grad():
                for p in layers.parameters():
                    sharding = getattr(p._data, "sharding", None)
                    if not isinstance(sharding, NamedSharding):
                        p._data = jax.device_put(
                            p._data,
                            NamedSharding(mesh.jax_mesh(), PartitionSpec(*([None] * p.ndim))),
                        )

    def forward(self, *inputs: Any, **kwargs: Any) -> Any:
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.set_state_dict(*args, **kwargs)
