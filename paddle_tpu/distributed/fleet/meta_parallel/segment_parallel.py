"""SegmentParallel (SEP) wrapper.

Reference: ``fleet/meta_parallel/segment_parallel.py:26`` — broadcasts params
across the sep group; the sequence split itself is model-side (attention must
be written sep-aware). TPU-native: the sep axis is a mesh dimension; inputs get
their sequence dim sharded over it, params stay replicated, and sep-aware
attention (ring attention, ``paddle_tpu.nn.functional.ring_attention``) runs on
the sharded sequence.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer


class SegmentParallel(Layer):
    def __init__(self, layers: Layer, hcg: Any = None, seq_axis: int = 1, **kwargs: Any) -> None:
        super().__init__()
        self._layers = layers
        self._seq_axis = seq_axis
        from paddle_tpu.distributed.fleet import fleet as _fleet
        from paddle_tpu.distributed.mesh import get_mesh

        self._hcg = hcg or _fleet.get_hybrid_communicate_group()
        self._mesh = get_mesh()
        self._sep_name = None
        if self._mesh is not None and "sep" in self._mesh.dim_names and self._mesh.get_dim_size("sep") > 1:
            self._sep_name = "sep"

    def _shard_seq(self, x: Any) -> Any:
        if self._sep_name is None or not isinstance(x, Tensor) or x.ndim <= self._seq_axis:
            return x
        entries: list = [None] * x.ndim
        entries[self._seq_axis] = self._sep_name
        arr = jax.device_put(x._data, NamedSharding(self._mesh.jax_mesh(), PartitionSpec(*entries)))
        return Tensor(arr, stop_gradient=x.stop_gradient)

    def forward(self, *inputs: Any, **kwargs: Any) -> Any:
        inputs = tuple(self._shard_seq(x) for x in inputs)
        kwargs = {k: self._shard_seq(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.set_state_dict(*args, **kwargs)
