"""Pipeline model description: LayerDesc / SharedLayerDesc / SegmentLayers /
PipelineLayer.

Reference: ``python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py`` (``LayerDesc:56``, ``SharedLayerDesc:76``, ``SegmentLayers:92``
with 'uniform' and 'layer:<Name>' methods ``:140``, ``PipelineLayer:257``).

TPU-native design: the reference instantiates only the local stage's layers on
each pp rank and wires p2p sends between ranks. Under single-controller SPMD
all stages are instantiated in the one global program; stage assignment
becomes *placement*: stage ``s``'s parameters can be left replicated (pure
grad-accumulation schedule), or — for homogeneous decoder stacks — stacked and
sharded over the ``pp`` mesh axis and executed by the shard_map circular
pipeline in ``spmd_pipeline.py``, which is where the 1F1B/GPipe overlap
actually happens on hardware.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.nn.layer.layers import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Lazy layer constructor (reference ``pp_layers.py:56``)."""

    def __init__(self, layer_func: Callable[..., Any], *inputs: Any, **kwargs: Any) -> None:
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        is_layer_cls = isinstance(layer_func, type) and issubclass(layer_func, Layer)
        if not is_layer_cls and not callable(layer_func):
            raise TypeError("The input of LayerDesc should be Layer or callable")

    def build_layer(self) -> Any:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self) -> str:
        name = getattr(self.layer_func, "__name__", str(self.layer_func))
        return f"LayerDesc({name})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared between pipeline stages — the tied
    input-embedding / output-projection pattern (reference ``pp_layers.py:76``).

    The reference broadcasts the shared weight across the pp group each step;
    in the global-view program both uses reference the *same* Parameter
    object, so sharing is structural and gradient accumulation over both uses
    is what autograd already does.
    """

    def __init__(
        self,
        key: str,
        layer_func: Callable[..., Any],
        forward_func: Optional[Callable[..., Any]] = None,
        shared_weight_attr: str = "weight",
        *inputs: Any,
        **kwargs: Any,
    ) -> None:
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition a layer list into ``num_parts`` contiguous stages
    (reference ``pp_layers.py:92``; methods at ``:140``)."""

    def __init__(
        self,
        layers_desc: Sequence[Any],
        num_parts: int,
        method: str = "uniform",
        num_virtual_pipeline_stage: Optional[int] = None,
    ) -> None:
        self._layers_desc = list(layers_desc)
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(self._layers_desc)
        if num_virtual_pipeline_stage is not None and num_virtual_pipeline_stage > 1:
            self.total_parts = num_parts * num_virtual_pipeline_stage
        else:
            self.total_parts = num_parts
        if self.num_items < self.total_parts:
            raise ValueError("layer number should be greater than number of segments")

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(self.num_items, self.total_parts)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            weights = self._gen_layer_weight(name)
            return self.segment_with_weights(weights)
        raise ValueError(f"unknown segment method {self.method!r}")

    @staticmethod
    def uniform(num_items: int, num_parts: int) -> List[int]:
        result = [0] * (num_parts + 1)
        part_size = num_items // num_parts
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result

    def _gen_layer_weight(self, layername: str) -> List[int]:
        """Weight 1 for each layer whose class name matches ``layername``
        (regex), 0 otherwise — boundaries land so each stage gets an equal
        count of the matched (transformer-block) layers."""
        weights = []
        regex = re.compile(layername)
        for desc in self._layers_desc:
            if isinstance(desc, LayerDesc):
                name = getattr(desc.layer_func, "__name__", "")
            else:
                name = desc.__class__.__name__
            weights.append(1 if regex.match(name) else 0)
        if sum(weights) == 0:
            raise ValueError(f"weight method {layername!r} matched no layers")
        return weights

    def segment_with_weights(self, weights: List[int]) -> List[int]:
        total = sum(weights)
        per_part, extra = divmod(total, self.total_parts)
        result = [0] * (self.total_parts + 1)
        memory = 0
        part = 1
        target = per_part + (1 if part <= extra else 0)
        for idx, w in enumerate(weights):
            memory += w
            if memory == target and part <= self.total_parts:
                result[part] = idx + 1
                part += 1
                memory = 0
                target = per_part + (1 if part <= extra else 0)
        result[self.total_parts] = len(weights)
        for i in range(1, self.total_parts + 1):
            if result[i] == 0:
                result[i] = result[i - 1]
        return result


class PipelineLayer(Layer):
    """A model described as a flat list of layers/LayerDescs, segmented into
    pipeline stages (reference ``pp_layers.py:257``).

    Global-view semantics: ``forward`` runs every stage in order (XLA sees
    one program). ``recompute_interval > 0`` wraps each chunk of that many
    layers in activation checkpointing, matching the reference's
    segment-level recompute.
    """

    def __init__(
        self,
        layers: Sequence[Any],
        num_stages: Optional[int] = None,
        topology: Any = None,
        loss_fn: Optional[Callable] = None,
        seg_method: str = "uniform",
        recompute_interval: int = 0,
        recompute_ctx: Optional[Dict[str, Any]] = None,
        num_virtual_pipeline_stages: Optional[int] = None,
    ) -> None:
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = num_virtual_pipeline_stages or 1
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = int(num_stages or 1)

        self._layers_desc = list(layers)
        self.segment_parts = SegmentLayers(
            self._layers_desc,
            num_parts=self._num_stages,
            method=seg_method,
            num_virtual_pipeline_stage=self._num_virtual_pipeline_stages,
        ).do_segment()

        # build all layers (global view); shared descs built once per key
        self.shared_layers: Dict[str, Any] = {}
        self._built: List[Any] = []
        self._shared_forward: Dict[int, Callable] = {}
        for i, desc in enumerate(self._layers_desc):
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self.shared_layers:
                    self.shared_layers[desc.layer_name] = desc.build_layer()
                    self.add_sublayer(f"shared_{desc.layer_name}", self.shared_layers[desc.layer_name])
                layer = self.shared_layers[desc.layer_name]
                if desc.forward_func is not None:
                    self._shared_forward[i] = desc.forward_func
                self._built.append(layer)
            elif isinstance(desc, LayerDesc):
                layer = desc.build_layer()
                self.add_sublayer(str(i), layer)
                self._built.append(layer)
            elif isinstance(desc, Layer):
                self.add_sublayer(str(i), desc)
                self._built.append(desc)
            elif callable(desc):
                self._built.append(desc)
            else:
                raise TypeError(f"invalid pipeline layer entry: {desc!r}")

    # --- introspection -------------------------------------------------
    @property
    def parts(self) -> List[int]:
        return self.segment_parts

    def get_num_stages(self) -> int:
        return self._num_stages

    def stage_of(self, layer_idx: int) -> int:
        """Which (virtual) stage a layer index belongs to."""
        for s in range(len(self.segment_parts) - 1):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s % self._num_stages
        raise IndexError(layer_idx)

    def get_stage_layers(self, stage: int) -> List[Any]:
        out: List[Any] = []
        for virt in range(self._num_virtual_pipeline_stages):
            part = virt * self._num_stages + stage
            out.extend(self._built[self.segment_parts[part] : self.segment_parts[part + 1]])
        return out

    def build_spmd_executor(
        self,
        mesh: Any,
        num_microbatches: int,
        axis_name: str = "pp",
        checkpoint_stages: bool = False,
        schedule: str = "auto",
    ) -> Any:
        """The TPU pipeline-parallel path: run this model's decoder region
        through the scan+ppermute circular executor with stage weights sharded
        over ``axis_name`` (see ``spmd_pipeline.SpmdPipelineExecutor``).
        Virtual stages (``num_virtual_pipeline_stages``) become ring laps.
        ``schedule``: ``auto`` (interleaved ring when V > 1, else circular
        1F1B analog) or ``zero_bubble`` (dx-only reverse ring + off-ring
        batched weight grads, reference ``pipeline_zero_bubble.py``)."""
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
            SpmdPipelineExecutor,
        )

        return SpmdPipelineExecutor(
            self,
            mesh,
            num_microbatches,
            axis_name=axis_name,
            checkpoint_stages=checkpoint_stages,
            schedule=schedule,
        )

    # --- execution -----------------------------------------------------
    def _run_one(self, i: int, layer: Any, x: Any) -> Any:
        if i in self._shared_forward:
            return self._shared_forward[i](layer, x)
        return layer(x)

    def forward(self, x: Any) -> Any:
        if self._recompute_interval <= 0:
            for i, layer in enumerate(self._built):
                x = self._run_one(i, layer, x)
            return x
        from paddle_tpu.distributed.fleet.recompute import recompute

        i = 0
        n = len(self._built)
        while i < n:
            j = min(i + self._recompute_interval, n)
            chunk = list(range(i, j))

            def run_chunk(x: Any, _chunk: List[int] = chunk) -> Any:
                for k in _chunk:
                    x = self._run_one(k, self._built[k], x)
                return x

            needs_grad = any(
                not p.stop_gradient
                for k in chunk
                if isinstance(self._built[k], Layer)
                for p in self._built[k].parameters()
            )
            x = recompute(run_chunk, x) if needs_grad else run_chunk(x)
            i = j
        return x
