"""Pipeline-parallel layer description API (reference
``fleet/meta_parallel/parallel_layers/``)."""

from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)
