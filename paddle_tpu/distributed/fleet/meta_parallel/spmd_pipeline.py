"""SPMD circular pipeline: the TPU-native pipeline-parallel executor.

Where the reference implements 1F1B as a per-rank Python event loop with NCCL
p2p (``meta_parallel/pipeline_parallel.py:547``, ``pp_utils/
p2p_communication.py:570``), on TPU the whole schedule is ONE compiled XLA
program: stage weights are stacked along a leading axis sharded over the
``pp`` mesh axis, and a ``lax.scan`` over pipeline ticks shifts activations
between neighbouring stages with ``lax.ppermute`` over ICI. XLA overlaps the
collective-permute with the next tick's stage compute (the same overlap the
1F1B event loop hand-codes), and ``jax.grad`` through the scan gives the
reversed schedule for backward for free.

Constraints: stages must be homogeneous (same activation shape in/out), which
holds for the decoder stacks PP is used on; embedding/head run outside the
pipelined region (they belong to first/last stages and are small).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pragma: no cover - jax<0.6 fallback
    import inspect

    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    _SM_PARAMS = inspect.signature(_experimental_shard_map).parameters

    def shard_map(f, mesh=None, **kw):  # type: ignore[misc]
        """New-API ``jax.shard_map`` surface over the experimental one:
        ``axis_names={...}`` becomes its complement in ``auto=``, and
        ``check_vma=`` maps back to its old name ``check_rep=``."""
        if "axis_names" in kw and "axis_names" not in _SM_PARAMS:
            axis_names = kw.pop("axis_names")
            auto = frozenset(getattr(mesh, "axis_names", ())) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        if "check_vma" in kw and "check_vma" not in _SM_PARAMS:
            kw["check_rep"] = kw.pop("check_vma")
        return _experimental_shard_map(f, mesh=mesh, **kw)

__all__ = [
    "pipeline",
    "pipeline_interleaved",
    "pipeline_zero_bubble",
    "stack_stage_params",
    "num_pipeline_ticks",
    "num_interleaved_ticks",
    "num_zero_bubble_ticks",
    "schedule_work_model",
    "plan_pipeline_region",
    "SpmdPipelineExecutor",
]


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack S per-stage parameter pytrees into one pytree whose leaves have a
    leading stage axis (to be sharded over the ``pp`` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *stage_params)


def num_pipeline_ticks(num_microbatches: int, num_stages: int) -> int:
    return num_microbatches + num_stages - 1


def num_interleaved_ticks(num_microbatches: int, num_stages: int, num_virtual: int) -> int:
    """Ticks for the interleaved ring schedule: ``V*M + S - 1`` — the V laps
    overlap, so the fill/drain bubble is paid once (S-1 ticks) instead of per
    lap (``V*(M+S-1)`` for sequential laps). Reference analog: the interleave
    scheduler of ``PipelineParallelWithInterleave`` /
    ``pipeline_scheduler_pass/pipeline_zero_bubble.py``'s bubble math."""
    return num_virtual * num_microbatches + num_stages - 1


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    microbatches: Any,
    mesh: Any,
    axis_name: str = "pp",
    mb_spec: Optional[P] = None,
    checkpoint_stages: bool = False,
) -> Any:
    """Run ``stage_fn`` as an S-stage circular pipeline over ``microbatches``.

    Args:
      stage_fn: ``(params, x) -> y`` for ONE stage; ``y.shape == x.shape``.
      stacked_params: pytree with leading stage axis S on every leaf
        (see :func:`stack_stage_params`), sharded ``P(axis_name)``.
      microbatches: ``[M, microbatch...]`` array — already embedded
        activations for a decoder stack.
      mesh: ``ProcessMesh`` or ``jax.sharding.Mesh`` containing ``axis_name``.
      mb_spec: PartitionSpec for the microbatch buffer over the *other* mesh
        axes (e.g. ``P(None, 'dp', None, None)`` to keep dp sharding of the
        batch dim); must be unsharded along ``axis_name``.
      checkpoint_stages: rematerialize stage activations in backward.

    Returns: ``[M, microbatch...]`` outputs, replicated over ``axis_name``.
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    if axis_name not in jmesh.shape:
        raise ValueError(f"mesh has no '{axis_name}' axis (axes: {list(jmesh.shape)})")
    S = jmesh.shape[axis_name]
    M = int(microbatches.shape[0])
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"stacked_params leading (stage) axis is {leaf.shape[0]} but the "
                f"'{axis_name}' mesh axis has {S} devices — one stage per device"
            )
    if S == 1:
        fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return jax.vmap(lambda x: fn(params0, x))(microbatches)
    if M % S != 0:
        raise ValueError(
            f"num microbatches ({M}) should be a multiple of pipeline stages ({S}) "
            "for full utilization"
        )
    if mb_spec is None:
        mb_spec = P()
    treedef = jax.tree.structure(stacked_params)
    mapped = _build_pipeline_callable(
        stage_fn, jmesh, axis_name, S, M, treedef, mb_spec, bool(checkpoint_stages)
    )
    return mapped(stacked_params, microbatches)


@functools.lru_cache(maxsize=32)  # bounded: each entry pins its stage_fn
def _build_pipeline_callable(
    stage_fn, jmesh, axis_name, S, M, param_treedef, mb_spec, checkpoint_stages
):
    """One jitted shard_map per static pipeline configuration — rebuilding the
    closure per call would defeat jax.jit's identity-keyed cache and recompile
    the whole scan+ppermute program every eager step."""
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
    T = num_pipeline_ticks(M, S)
    param_specs = jax.tree_util.tree_unflatten(
        param_treedef, [P(axis_name)] * param_treedef.num_leaves
    )
    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def local_fn(params: Any, mb: Any) -> Any:
        params = jax.tree.map(lambda a: a[0], params)  # this device's stage
        idx = jax.lax.axis_index(axis_name)
        state = jnp.zeros_like(mb[0])
        outputs = jnp.zeros_like(mb)

        def tick(carry: Any, t: Any) -> Any:
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(
                mb, jnp.minimum(t, M - 1), axis=0, keepdims=False
            )
            x = jnp.where(idx == 0, inject, state)
            y = fn(params, x)
            out_t = t - (S - 1)
            safe_t = jnp.clip(out_t, 0, M - 1)
            valid = jnp.logical_and(idx == S - 1, out_t >= 0)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe_t, axis=0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, cur), safe_t, 0
            )
            state = jax.lax.ppermute(y, axis_name, fwd_perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(tick, (state, outputs), jnp.arange(T))
        # replicate the last stage's result to every pp rank
        outputs = jax.lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis_name
        )
        return outputs

    # manual only over the pp axis: every other mesh axis (dp/mp/...) stays
    # automatic, so GSPMD keeps propagating batch/tensor shardings through the
    # stage compute — specs may only mention `axis_name`. Partial-manual
    # shard_map only lowers inside a jit scope, so wrap the call (a no-op
    # nesting when the caller is already tracing).
    mapped = shard_map(
        local_fn,
        mesh=jmesh,
        in_specs=(param_specs, mb_spec),
        out_specs=mb_spec,
        axis_names={axis_name},
        check_vma=False,
    )
    return jax.jit(mapped)


def pipeline_interleaved(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params_sv: Any,
    microbatches: Any,
    mesh: Any,
    num_virtual: int,
    axis_name: str = "pp",
    mb_spec: Optional[P] = None,
    checkpoint_stages: bool = False,
) -> Any:
    """Interleaved circular pipeline: device s holds V parameter chunks
    (virtual stages ``v*S + s``); ONE scan drives all V laps concurrently
    over a wrapped ring, so microbatch m on lap v occupies device s exactly
    at tick ``v*M + m + s`` — no device contention for ``M >= S``, and the
    warmup/drain bubble is paid once.

    ``stacked_params_sv``: pytree with leading axes ``[S, V, ...]`` on every
    leaf (stage-major, then lap). Requires ``M >= S`` (else a lap-v microbatch
    would need its lap-(v-1) result before the ring delivers it).
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    if axis_name not in jmesh.shape:
        raise ValueError(f"mesh has no '{axis_name}' axis (axes: {list(jmesh.shape)})")
    S = jmesh.shape[axis_name]
    V = int(num_virtual)
    M = int(microbatches.shape[0])
    if V < 2:
        raise ValueError("pipeline_interleaved needs num_virtual >= 2; use pipeline()")
    if M % S != 0 or M < S:
        raise ValueError(
            f"interleaved schedule needs num_microbatches ({M}) to be a multiple "
            f"of num_stages ({S}) and >= it"
        )
    for leaf in jax.tree.leaves(stacked_params_sv):
        if leaf.shape[0] != S or leaf.shape[1] != V:
            raise ValueError(
                f"stacked_params_sv leaves need leading [S={S}, V={V}] axes, "
                f"got {leaf.shape[:2]}"
            )
    if mb_spec is None:
        mb_spec = P()
    treedef = jax.tree.structure(stacked_params_sv)
    mapped = _build_interleaved_callable(
        stage_fn, jmesh, axis_name, S, V, M, treedef, mb_spec, bool(checkpoint_stages)
    )
    return mapped(stacked_params_sv, microbatches)


@functools.lru_cache(maxsize=32)
def _build_interleaved_callable(
    stage_fn, jmesh, axis_name, S, V, M, param_treedef, mb_spec, checkpoint_stages
):
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
    T = num_interleaved_ticks(M, S, V)
    param_specs = jax.tree_util.tree_unflatten(
        param_treedef, [P(axis_name)] * param_treedef.num_leaves
    )
    ring_perm = [(i, (i + 1) % S) for i in range(S)]

    def local_fn(params: Any, mb: Any) -> Any:
        params = jax.tree.map(lambda a: a[0], params)  # [V, ...] on this device
        idx = jax.lax.axis_index(axis_name)
        state = jnp.zeros_like(mb[0])
        wrap_buf = jnp.zeros_like(mb)  # device 0: lap v inputs keyed by m
        outputs = jnp.zeros_like(mb)

        def tick(carry: Any, t: Any) -> Any:
            state, wrap_buf, outputs = carry
            # 1) bank the ring-wrapped activation (device S-1 produced it at
            #    t-1 with phase t-S): it is microbatch (t-S)%M entering lap
            #    (t-S)//M + 1 at device 0, consumed at tick ((t-S)//M+1)*M+(t-S)%M
            prod_phase = t - S
            wrap_ok = jnp.logical_and(
                jnp.logical_and(idx == 0, prod_phase >= 0),
                (prod_phase // M) < (V - 1),
            )
            slot = jnp.clip(jnp.where(prod_phase >= 0, prod_phase % M, 0), 0, M - 1)
            cur_slot = jax.lax.dynamic_index_in_dim(wrap_buf, slot, 0, keepdims=False)
            wrap_buf = jax.lax.dynamic_update_index_in_dim(
                wrap_buf, jnp.where(wrap_ok, state, cur_slot), slot, 0
            )
            # 2) my (lap, microbatch) this tick
            phase = jnp.clip(t - idx, 0, V * M - 1)
            v = phase // M
            m = phase % M
            params_v = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False), params
            )
            fresh = jax.lax.dynamic_index_in_dim(mb, m, 0, keepdims=False)
            banked = jax.lax.dynamic_index_in_dim(wrap_buf, m, 0, keepdims=False)
            x = jnp.where(idx == 0, jnp.where(v == 0, fresh, banked), state)
            y = fn(params_v, x)
            # 3) final-lap outputs leave at device S-1
            out_ok = jnp.logical_and(
                jnp.logical_and(idx == S - 1, v == V - 1), t - idx >= 0
            )
            cur_out = jax.lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(out_ok, y, cur_out), m, 0
            )
            # 4) ring step (wraps S-1 -> 0 for the next lap)
            state = jax.lax.ppermute(y, axis_name, ring_perm)
            return (state, wrap_buf, outputs), None

        (state, wrap_buf, outputs), _ = jax.lax.scan(
            tick, (state, wrap_buf, outputs), jnp.arange(T)
        )
        idx = jax.lax.axis_index(axis_name)
        outputs = jax.lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis_name
        )
        return outputs

    mapped = shard_map(
        local_fn,
        mesh=jmesh,
        in_specs=(param_specs, mb_spec),
        out_specs=mb_spec,
        axis_names={axis_name},
        check_vma=False,
    )
    return jax.jit(mapped)


# --------------------------------------------------------------------------
# Zero-bubble schedule (reference
# ``distributed/passes/pipeline_scheduler_pass/pipeline_zero_bubble.py``).
#
# The reference's ZB-H1 splits each backward into an input-grad phase (on the
# p2p critical path) and a weight-grad phase scheduled into the drain bubble.
# The TPU-native expression goes further: differentiating *through* the scan
# (what ``pipeline``/``pipeline_interleaved`` do) makes every reverse ring
# tick compute remat-forward + dx + dW serially; here a custom VJP makes the
# reverse scan carry ONLY the dx chain (banking each microbatch's incoming
# cotangent), and ALL weight grads are computed after the ring drains as one
# batched ``vmap`` over microbatches — dW isn't squeezed into bubbles, it
# leaves the serialized path entirely and runs as large MXU-friendly batched
# contractions. See :func:`schedule_work_model` for the resulting tick-cost
# accounting used by the tests.
# --------------------------------------------------------------------------


def num_zero_bubble_ticks(num_microbatches: int, num_stages: int, num_virtual: int = 1) -> int:
    """Ring ticks per direction for the zero-bubble schedule — the forward
    ring and the dx-only reverse ring each take ``V*M + S - 1`` ticks (the
    interleaved ring length); the weight-grad phase adds NO ring ticks."""
    return num_virtual * num_microbatches + num_stages - 1


def schedule_work_model(schedule: str, S: int, M: int, V: int = 1) -> dict:
    """Analytic per-device work accounting for the pipeline schedules, in
    units of one stage-forward evaluation (fwd = 1; a dx-only backward with
    remat costs 2: recompute + input-grad matmuls; a full VJP with remat
    costs 3: recompute + input-grad + weight-grad).

    Returns
      ``ring_ticks``      ticks on the serialized ppermute ring (fwd + bwd)
      ``critical_path``   total serialized work units along the ring
      ``idle_work``       work units a device burns on masked (non-real) data
                          during warmup/drain — the "bubble", measured as
                          wasted compute in the lockstep SPMD schedule
      ``offring_work``    work units done outside the ring (fully batched,
                          zero bubble by construction)
    """
    if schedule in ("1f1b", "pipeline"):
        T = V * (M + S - 1)  # V sequential laps of the circular schedule
        return {
            "ring_ticks": 2 * T,
            "critical_path": T * 1 + T * 3,
            "idle_work": V * (S - 1) * (1 + 3),
            "offring_work": 0,
        }
    if schedule == "interleaved":
        T = num_interleaved_ticks(M, S, V)
        return {
            "ring_ticks": 2 * T,
            "critical_path": T * 1 + T * 3,
            "idle_work": (S - 1) * (1 + 3),
            "offring_work": 0,
        }
    if schedule == "zero_bubble":
        T = num_zero_bubble_ticks(M, S, V)
        return {
            "ring_ticks": 2 * T,
            "critical_path": T * 1 + T * 2,  # reverse ring is dx-only
            "idle_work": (S - 1) * (1 + 2),
            "offring_work": V * M * 2,  # batched remat + dW, no bubble
        }
    raise ValueError(f"unknown schedule {schedule!r}")


def pipeline_zero_bubble(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    microbatches: Any,
    mesh: Any,
    num_virtual: int = 1,
    axis_name: str = "pp",
    mb_spec: Optional[P] = None,
) -> Any:
    """Zero-bubble circular pipeline: forward identical to the (interleaved)
    ring schedule; backward = dx-only reverse ring + off-ring batched dW.

    Args match :func:`pipeline_interleaved`; ``stacked_params`` leaves carry
    ``[S, ...]`` when ``num_virtual == 1`` or ``[S, V, ...]`` when ``V > 1``.
    Activations are rematerialized in backward (zero-bubble implies
    checkpointing: only stage INPUTS are saved, once per microbatch-lap).
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    if axis_name not in jmesh.shape:
        raise ValueError(f"mesh has no '{axis_name}' axis (axes: {list(jmesh.shape)})")
    S = jmesh.shape[axis_name]
    V = int(num_virtual)
    M = int(microbatches.shape[0])
    if V < 1:
        raise ValueError("num_virtual must be >= 1")
    lead = (S,) if V == 1 else (S, V)
    for leaf in jax.tree.leaves(stacked_params):
        if tuple(leaf.shape[: len(lead)]) != lead:
            raise ValueError(
                f"stacked_params leaves need leading {list(lead)} axes, got "
                f"{leaf.shape[: len(lead)]}"
            )
    if S == 1:
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        out = microbatches
        for v in range(V):
            pv = params0 if V == 1 else jax.tree.map(lambda a, v=v: a[v], params0)
            out = jax.vmap(lambda x, pv=pv: stage_fn(pv, x))(out)
        return out
    if M % S != 0 or M < S:
        raise ValueError(
            f"zero-bubble schedule needs num_microbatches ({M}) to be a "
            f"multiple of num_stages ({S}) and >= it"
        )
    if V == 1:  # normalize to the [S, V, ...] layout internally
        stacked_params = jax.tree.map(lambda a: a[:, None], stacked_params)
    if mb_spec is None:
        mb_spec = P()
    treedef = jax.tree.structure(stacked_params)
    mapped = _build_zero_bubble_callable(
        stage_fn, jmesh, axis_name, S, V, M, treedef, mb_spec
    )
    return mapped(stacked_params, microbatches)


@functools.lru_cache(maxsize=32)
def _build_zero_bubble_callable(stage_fn, jmesh, axis_name, S, V, M, param_treedef, mb_spec):
    """Custom-VJP pipeline: forward ring (+ input banking), dx-only reverse
    ring (+ cotangent banking), batched off-ring weight-grad phase. The
    reverse schedule is the forward schedule under the relabeling
    ``idx -> S-1-idx``, ``m -> M-1-m``, ``v -> V-1-v`` with the ring running
    backwards — so the two scans share their structure."""
    T = num_zero_bubble_ticks(M, S, V)
    param_specs = jax.tree_util.tree_unflatten(
        param_treedef, [P(axis_name)] * param_treedef.num_leaves
    )
    fwd_ring = [(i, (i + 1) % S) for i in range(S)]
    rev_ring = [(i, (i - 1) % S) for i in range(S)]
    # banked buffers carry one entry per (lap, microbatch) phase slot; in
    # partial-manual shard_map, specs may only mention the manual pp axis —
    # other mesh axes (dp/...) stay automatic on the trailing dims
    save_spec = P(axis_name)

    def local_fwd(params, mb):
        params = jax.tree.map(lambda a: a[0], params)  # [V, ...] on this device
        idx = jax.lax.axis_index(axis_name)
        state = jnp.zeros_like(mb[0])
        wrap_buf = jnp.zeros_like(mb)
        outputs = jnp.zeros_like(mb)
        xsave = jnp.zeros(
            (V * M,) + mb.shape[1:], mb.dtype
        )  # my stage's input per phase

        def tick(carry, t):
            state, wrap_buf, outputs, xsave = carry
            prod_phase = t - S
            wrap_ok = jnp.logical_and(
                jnp.logical_and(idx == 0, prod_phase >= 0),
                (prod_phase // M) < (V - 1),
            )
            slot = jnp.clip(jnp.where(prod_phase >= 0, prod_phase % M, 0), 0, M - 1)
            cur_slot = jax.lax.dynamic_index_in_dim(wrap_buf, slot, 0, keepdims=False)
            wrap_buf = jax.lax.dynamic_update_index_in_dim(
                wrap_buf, jnp.where(wrap_ok, state, cur_slot), slot, 0
            )
            phase = jnp.clip(t - idx, 0, V * M - 1)
            valid = jnp.logical_and(t - idx >= 0, t - idx < V * M)
            v = phase // M
            m = phase % M
            params_v = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False), params
            )
            fresh = jax.lax.dynamic_index_in_dim(mb, m, 0, keepdims=False)
            banked = jax.lax.dynamic_index_in_dim(wrap_buf, m, 0, keepdims=False)
            x = jnp.where(idx == 0, jnp.where(v == 0, fresh, banked), state)
            cur_x = jax.lax.dynamic_index_in_dim(xsave, phase, 0, keepdims=False)
            xsave = jax.lax.dynamic_update_index_in_dim(
                xsave, jnp.where(valid, x, cur_x), phase, 0
            )
            y = stage_fn(params_v, x)
            out_ok = jnp.logical_and(
                jnp.logical_and(idx == S - 1, v == V - 1), valid
            )
            cur_out = jax.lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(out_ok, y, cur_out), m, 0
            )
            state = jax.lax.ppermute(y, axis_name, fwd_ring)
            return (state, wrap_buf, outputs, xsave), None

        (state, wrap_buf, outputs, xsave), _ = jax.lax.scan(
            tick, (state, wrap_buf, outputs, xsave), jnp.arange(T)
        )
        outputs = jax.lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis_name
        )
        return outputs, xsave

    def local_bwd(params, xsave, g):
        params = jax.tree.map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis_name)
        idx_r = S - 1 - idx  # reverse-schedule stage index
        state = jnp.zeros_like(g[0])
        wrap_buf = jnp.zeros_like(g)
        dmb = jnp.zeros_like(g)
        dysave = jnp.zeros((V * M,) + g.shape[1:], g.dtype)

        def tick(carry, u):
            state, wrap_buf, dmb, dysave = carry
            # reverse wrap: device idx_r==0 (global last stage) banks the
            # cotangent ring-wrapped from idx_r==S-1 for the next reverse lap
            prod_phase = u - S
            wrap_ok = jnp.logical_and(
                jnp.logical_and(idx_r == 0, prod_phase >= 0),
                (prod_phase // M) < (V - 1),
            )
            slot = jnp.clip(jnp.where(prod_phase >= 0, prod_phase % M, 0), 0, M - 1)
            cur_slot = jax.lax.dynamic_index_in_dim(wrap_buf, slot, 0, keepdims=False)
            wrap_buf = jax.lax.dynamic_update_index_in_dim(
                wrap_buf, jnp.where(wrap_ok, state, cur_slot), slot, 0
            )
            phase_r = jnp.clip(u - idx_r, 0, V * M - 1)
            valid = jnp.logical_and(u - idx_r >= 0, u - idx_r < V * M)
            m_r = phase_r % M
            phase = V * M - 1 - phase_r  # actual (lap, microbatch) slot
            v = phase // M
            m = phase % M
            params_v = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False), params
            )
            fresh = jax.lax.dynamic_index_in_dim(g, m, 0, keepdims=False)
            banked = jax.lax.dynamic_index_in_dim(wrap_buf, m_r, 0, keepdims=False)
            v_r = phase_r // M
            dy = jnp.where(idx_r == 0, jnp.where(v_r == 0, fresh, banked), state)
            cur_dy = jax.lax.dynamic_index_in_dim(dysave, phase, 0, keepdims=False)
            dysave = jax.lax.dynamic_update_index_in_dim(
                dysave, jnp.where(valid, dy, cur_dy), phase, 0
            )
            x = jax.lax.dynamic_index_in_dim(xsave, phase, 0, keepdims=False)
            # dx-only VJP: remat the stage forward, push the cotangent
            # through the input path; dW is deliberately NOT computed here
            _, vjp_x = jax.vjp(lambda xx: stage_fn(params_v, xx), x)
            (dx,) = vjp_x(dy)
            out_ok = jnp.logical_and(
                jnp.logical_and(idx_r == S - 1, v_r == V - 1), valid
            )
            cur_dmb = jax.lax.dynamic_index_in_dim(dmb, m, 0, keepdims=False)
            dmb = jax.lax.dynamic_update_index_in_dim(
                dmb, jnp.where(out_ok, dx, cur_dmb), m, 0
            )
            state = jax.lax.ppermute(dx, axis_name, rev_ring)
            return (state, wrap_buf, dmb, dysave), None

        (state, wrap_buf, dmb, dysave), _ = jax.lax.scan(
            tick, (state, wrap_buf, dmb, dysave), jnp.arange(T)
        )
        # off-ring weight-grad phase: one batched remat+dW contraction per
        # lap over all M microbatches at once — no ring, no bubble
        xs = xsave.reshape((V, M) + xsave.shape[1:])
        dys = dysave.reshape((V, M) + dysave.shape[1:])
        per_lap = []
        for v in range(V):
            pv = jax.tree.map(lambda a, v=v: a[v], params)

            def wgrad_one(x, dy, pv=pv):
                _, vjp_p = jax.vjp(lambda q: stage_fn(q, x), pv)
                return vjp_p(dy)[0]

            contrib = jax.vmap(wgrad_one)(xs[v], dys[v])
            per_lap.append(jax.tree.map(lambda a: a.sum(0), contrib))
        dparams = jax.tree.map(lambda *leaves: jnp.stack(leaves, 0), *per_lap)
        dparams = jax.tree.map(lambda a: a[None], dparams)  # local [1, V, ...]
        dmb = jax.lax.psum(
            jnp.where(idx == 0, dmb, jnp.zeros_like(dmb)), axis_name
        )
        return dparams, dmb

    mapped_fwd = jax.jit(
        shard_map(
            local_fwd,
            mesh=jmesh,
            in_specs=(param_specs, mb_spec),
            out_specs=(mb_spec, save_spec),
            axis_names={axis_name},
            check_vma=False,
        )
    )
    mapped_bwd = jax.jit(
        shard_map(
            local_bwd,
            mesh=jmesh,
            in_specs=(param_specs, save_spec, mb_spec),
            out_specs=(param_specs, mb_spec),
            axis_names={axis_name},
            check_vma=False,
        )
    )

    @jax.custom_vjp
    def pzb(stacked_params, mb):
        return mapped_fwd(stacked_params, mb)[0]

    def pzb_f(stacked_params, mb):
        out, xsave = mapped_fwd(stacked_params, mb)
        return out, (stacked_params, xsave)

    def pzb_b(res, gy):
        stacked_params, xsave = res
        return mapped_bwd(stacked_params, xsave, gy)

    pzb.defvjp(pzb_f, pzb_b)
    return jax.jit(pzb)


# --------------------------------------------------------------------------
# PipelineLayer wiring: run a model's homogeneous decoder region through the
# circular executor (the reference runs 1F1B/interleave event loops instead:
# ``meta_parallel/pipeline_parallel.py:547`` / ``:1138``)
# --------------------------------------------------------------------------


def _structure_key(layer: Any) -> Any:
    """Structural fingerprint: two layers with the same key can be executed by
    one template function with swapped parameters."""
    from paddle_tpu.nn.layer.layers import Layer as _Layer

    if not isinstance(layer, _Layer):
        return None
    return (
        type(layer).__qualname__,
        tuple(
            (n, tuple(p.shape), str(p.dtype)) for n, p in layer.named_parameters()
        ),
    )


def plan_pipeline_region(pipe: Any) -> tuple:
    """Find the maximal contiguous run of structurally identical layers in a
    ``PipelineLayer`` — the homogeneous decoder stack that the SPMD circular
    pipeline executes. Returns ``(start, end)`` into ``pipe._built``;
    ``[0, start)`` is the prologue (embeddings), ``[end, len)`` the epilogue
    (final norm, lm head)."""
    keys = [_structure_key(l) for l in pipe._built]  # noqa: E741
    best = (0, 0)
    i = 0
    n = len(keys)
    while i < n:
        if keys[i] is None:
            i += 1
            continue
        j = i
        while j < n and keys[j] == keys[i]:
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    if best[1] - best[0] < 2:
        raise ValueError(
            "PipelineLayer has no homogeneous region of >= 2 layers; the SPMD "
            "circular pipeline needs a repeated decoder block structure"
        )
    return best


class SpmdPipelineExecutor:
    """Execute a ``PipelineLayer`` with its decoder region pipelined over the
    ``pp`` mesh axis via the scan+ppermute circular schedule.

    Prologue/epilogue layers (embedding, final norm, tied lm head) run in the
    global program on every rank — they are small, and the tied-embedding
    gradient accumulation falls out of autograd because both uses reference
    the same Parameter. The region's blocks are assigned to stages in
    contiguous chunks; with ``num_virtual_pipeline_stages = V > 1`` each stage
    holds V chunks and the schedule makes V laps around the ring
    (the interleave topology of reference ``PipelineParallelWithInterleave``,
    expressed as stacked virtual stages rather than an event loop).

    Differentiable end-to-end: the pipelined region is dispatched as one op
    whose VJP is jax-derived, so ``loss.backward()`` reaches every block
    parameter as well as the prologue/epilogue ones.
    """

    def __init__(
        self,
        pipe: Any,
        mesh: Any,
        num_microbatches: int,
        axis_name: str = "pp",
        checkpoint_stages: bool = False,
        schedule: str = "auto",
    ) -> None:
        if schedule not in ("auto", "zero_bubble"):
            raise ValueError(f"schedule must be 'auto' or 'zero_bubble', got {schedule!r}")
        self._pipe = pipe
        self._mesh = mesh
        self._axis = axis_name
        self._M = int(num_microbatches)
        self._ckpt = checkpoint_stages
        self._schedule = schedule
        jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
        if axis_name not in jmesh.shape:
            raise ValueError(
                f"mesh has no '{axis_name}' axis (axes: {list(jmesh.shape)})"
            )
        self._S = int(jmesh.shape[axis_name])
        self._V = int(getattr(pipe, "_num_virtual_pipeline_stages", 1) or 1)
        start, end = plan_pipeline_region(pipe)
        self._start, self._end = start, end
        L = end - start
        if L % (self._S * self._V) != 0:
            raise ValueError(
                f"decoder region has {L} blocks, not divisible by "
                f"num_stages*virtual ({self._S}*{self._V})"
            )
        self._C = L // (self._S * self._V)  # blocks per (stage, lap) chunk
        if schedule == "zero_bubble" and self._S > 1 and (
            self._M < self._S or self._M % self._S != 0
        ):
            raise ValueError(
                f"zero_bubble schedule needs num_microbatches ({self._M}) to be "
                f"a multiple of num_stages ({self._S}) and >= it"
            )
        self._blocks = pipe._built[start:end]
        self._template = self._blocks[0]
        self._param_names = [n for n, _ in self._template.named_parameters()]
        if not self._param_names:
            raise ValueError("pipelined blocks have no parameters")

    # -- template application (pure-jax view of one block) ------------------
    def _apply_template(self, arrays: List[Any], x: Any) -> Any:
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor

        named = list(self._template.named_parameters())
        saved = [p._data for _, p in named]
        try:
            for (_n, p), a in zip(named, arrays):
                p._data = a
            with paddle_tpu.no_grad():
                y = self._template(Tensor(x))
            return y._data
        finally:
            for (_n, p), s in zip(named, saved):
                p._data = s

    def _chunk_fn(self, chunk_params: List[List[Any]], x: Any) -> Any:
        for block_arrays in chunk_params:
            x = self._apply_template(block_arrays, x)
        return x

    # -- full forward -------------------------------------------------------
    def forward(self, x: Any) -> Any:
        from paddle_tpu.core.dispatch import call_op

        pipe, M, S, V, C = self._pipe, self._M, self._S, self._V, self._C
        h = x
        for i in range(self._start):
            h = pipe._run_one(i, pipe._built[i], h)

        batch = h.shape[0]
        if batch % M != 0:
            raise ValueError(f"batch {batch} not divisible by num_microbatches {M}")
        per_block_tensors = [
            [dict(b.named_parameters())[n] for n in self._param_names]
            for b in self._blocks
        ]
        flat_params = [t for row in per_block_tensors for t in row]
        P_ = len(self._param_names)

        def stack_sv(rows, with_lap_axis):
            """[S, V, ...] (stage-major, then lap) stacking of the per-block
            parameter rows; ``with_lap_axis=False`` keeps plain [S, ...]."""
            per_sv = [
                [rows[(v * S + s) * C : (v * S + s + 1) * C] for v in range(V)]
                for s in range(S)
            ]
            if not with_lap_axis:
                return jax.tree.map(
                    lambda *xs: jnp.stack(xs, 0), *[per_sv[s][0] for s in range(S)]
                )
            lap_stacked = [
                jax.tree.map(lambda *xs: jnp.stack(xs, 0), *per_sv[s])
                for s in range(S)
            ]
            return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *lap_stacked)

        def impl(h_arr, *flat):
            rows = [list(flat[i * P_ : (i + 1) * P_]) for i in range(len(self._blocks))]
            mb = h_arr.reshape((M, batch // M) + h_arr.shape[1:])
            if self._schedule == "zero_bubble" and S > 1 and M >= S:
                mb = pipeline_zero_bubble(
                    self._chunk_fn,
                    stack_sv(rows, with_lap_axis=V > 1),
                    mb,
                    self._mesh,
                    num_virtual=V,
                    axis_name=self._axis,
                )
            elif V > 1 and S > 1 and M >= S:
                # interleaved ring: all V laps overlap in ONE scan —
                # V*M + S - 1 ticks instead of V*(M + S - 1)
                mb = pipeline_interleaved(
                    self._chunk_fn,
                    stack_sv(rows, with_lap_axis=True),
                    mb,
                    self._mesh,
                    V,
                    axis_name=self._axis,
                    checkpoint_stages=self._ckpt,
                )
            else:
                for v in range(V):
                    stage_chunks = [
                        rows[(v * S + s) * C : (v * S + s + 1) * C] for s in range(S)
                    ]
                    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *stage_chunks)
                    mb = pipeline(
                        self._chunk_fn,
                        stacked,
                        mb,
                        self._mesh,
                        axis_name=self._axis,
                        checkpoint_stages=self._ckpt,
                    )
            return mb.reshape((batch,) + mb.shape[2:])

        h = call_op("spmd_pipeline", impl, h, *flat_params)
        for i in range(self._end, len(pipe._built)):
            h = pipe._run_one(i, pipe._built[i], h)
        return h

    __call__ = forward
