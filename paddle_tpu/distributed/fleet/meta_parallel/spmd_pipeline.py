"""SPMD circular pipeline: the TPU-native pipeline-parallel executor.

Where the reference implements 1F1B as a per-rank Python event loop with NCCL
p2p (``meta_parallel/pipeline_parallel.py:547``, ``pp_utils/
p2p_communication.py:570``), on TPU the whole schedule is ONE compiled XLA
program: stage weights are stacked along a leading axis sharded over the
``pp`` mesh axis, and a ``lax.scan`` over pipeline ticks shifts activations
between neighbouring stages with ``lax.ppermute`` over ICI. XLA overlaps the
collective-permute with the next tick's stage compute (the same overlap the
1F1B event loop hand-codes), and ``jax.grad`` through the scan gives the
reversed schedule for backward for free.

Constraints: stages must be homogeneous (same activation shape in/out), which
holds for the decoder stacks PP is used on; embedding/head run outside the
pipelined region (they belong to first/last stages and are small).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pragma: no cover - jax<0.6 fallback
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["pipeline", "stack_stage_params", "num_pipeline_ticks"]


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack S per-stage parameter pytrees into one pytree whose leaves have a
    leading stage axis (to be sharded over the ``pp`` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *stage_params)


def num_pipeline_ticks(num_microbatches: int, num_stages: int) -> int:
    return num_microbatches + num_stages - 1


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    microbatches: Any,
    mesh: Any,
    axis_name: str = "pp",
    mb_spec: Optional[P] = None,
    checkpoint_stages: bool = False,
) -> Any:
    """Run ``stage_fn`` as an S-stage circular pipeline over ``microbatches``.

    Args:
      stage_fn: ``(params, x) -> y`` for ONE stage; ``y.shape == x.shape``.
      stacked_params: pytree with leading stage axis S on every leaf
        (see :func:`stack_stage_params`), sharded ``P(axis_name)``.
      microbatches: ``[M, microbatch...]`` array — already embedded
        activations for a decoder stack.
      mesh: ``ProcessMesh`` or ``jax.sharding.Mesh`` containing ``axis_name``.
      mb_spec: PartitionSpec for the microbatch buffer over the *other* mesh
        axes (e.g. ``P(None, 'dp', None, None)`` to keep dp sharding of the
        batch dim); must be unsharded along ``axis_name``.
      checkpoint_stages: rematerialize stage activations in backward.

    Returns: ``[M, microbatch...]`` outputs, replicated over ``axis_name``.
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis_name]
    M = int(microbatches.shape[0])
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"stacked_params leading (stage) axis is {leaf.shape[0]} but the "
                f"'{axis_name}' mesh axis has {S} devices — one stage per device"
            )
    if S == 1:
        fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return jax.vmap(lambda x: fn(params0, x))(microbatches)
    if M % S != 0:
        raise ValueError(
            f"num microbatches ({M}) should be a multiple of pipeline stages ({S}) "
            "for full utilization"
        )
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
    T = num_pipeline_ticks(M, S)
    if mb_spec is None:
        mb_spec = P()
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def local_fn(params: Any, mb: Any) -> Any:
        params = jax.tree.map(lambda a: a[0], params)  # this device's stage
        idx = jax.lax.axis_index(axis_name)
        state = jnp.zeros_like(mb[0])
        outputs = jnp.zeros_like(mb)

        def tick(carry: Any, t: Any) -> Any:
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(
                mb, jnp.minimum(t, M - 1), axis=0, keepdims=False
            )
            x = jnp.where(idx == 0, inject, state)
            y = fn(params, x)
            out_t = t - (S - 1)
            safe_t = jnp.clip(out_t, 0, M - 1)
            valid = jnp.logical_and(idx == S - 1, out_t >= 0)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe_t, axis=0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, cur), safe_t, 0
            )
            state = jax.lax.ppermute(y, axis_name, fwd_perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(tick, (state, outputs), jnp.arange(T))
        # replicate the last stage's result to every pp rank
        outputs = jax.lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis_name
        )
        return outputs

    return shard_map(
        local_fn,
        mesh=jmesh,
        in_specs=(param_specs, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(stacked_params, microbatches)
