"""Meta-parallel model wrappers (reference ``fleet/meta_parallel/``)."""

from paddle_tpu.distributed.fleet.meta_parallel.segment_parallel import SegmentParallel  # noqa: F401
from paddle_tpu.distributed.fleet.meta_parallel.tensor_parallel import TensorParallel  # noqa: F401
