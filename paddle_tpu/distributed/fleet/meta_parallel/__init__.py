"""Meta-parallel model wrappers (reference ``fleet/meta_parallel/``)."""

from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (  # noqa: F401
    PipelineParallel,
    PipelineParallelWithInterleave,
)
from paddle_tpu.distributed.fleet.meta_parallel.segment_parallel import SegmentParallel  # noqa: F401
from paddle_tpu.distributed.fleet.meta_parallel.tensor_parallel import TensorParallel  # noqa: F401
