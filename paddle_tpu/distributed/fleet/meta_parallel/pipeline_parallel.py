"""Pipeline-parallel runtime: microbatch schedules.

Reference: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(``PipelineParallel:231``, ``forward_backward_pipeline:547`` — the 1F1B
schedule, ``PipelineParallelWithInterleave:1138`` — virtual stages/VPP,
``...FthenB:1964``).

TPU-native design: the reference's schedule is a hand-rolled event loop of
p2p sends between per-rank processes. Under XLA the pipelined overlap is a
*compiler/placement* concern (see ``spmd_pipeline.py`` for the shard_map
circular schedule); what remains at this layer is the *numerics* of the
schedule — microbatch splitting, loss scaling by 1/num_microbatches, gradient
accumulation across microbatches, shared-embedding gradient ties — which are
identical for FThenB, 1F1B and VPP (they differ only in memory/overlap).
Each microbatch's fwd+bwd runs as its own XLA program; gradients accumulate
into ``param.grad`` exactly as the reference accumulates across micro-steps.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import PipelineLayer
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


def _split_micro(data: Any, num: int) -> List[Any]:
    """Split a batch (tensor or tuple/list of tensors) into ``num``
    microbatches along axis 0. None and python scalars are replicated;
    array-likes must be Tensors so the split is explicit."""
    if isinstance(data, (tuple, list)):
        parts = [_split_micro(d, num) for d in data]
        return [type(data)(p[i] for p in parts) for i in range(num)]
    if isinstance(data, Tensor):
        bs = data.shape[0]
        if bs % num != 0:
            raise ValueError(f"batch size {bs} not divisible by accumulate_steps {num}")
        mb = bs // num
        return [data[i * mb : (i + 1) * mb] for i in range(num)]
    if data is None or isinstance(data, (bool, int, float)):
        return [data] * num
    raise TypeError(
        f"pipeline batch entries must be Tensors (or None/scalars), got {type(data)}; "
        "wrap arrays with paddle.to_tensor"
    )


class PipelineParallel(Layer):
    """Microbatched pipeline training wrapper (reference
    ``pipeline_parallel.py:231``)."""

    def __init__(self, layers: Any, hcg: Any = None, strategy: Any = None) -> None:
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        acc = 1
        if strategy is not None:
            # accepted spellings: strategy.pipeline_configs['accumulate_steps']
            # (this DistributedStrategy's declared field) and
            # hybrid_configs['pp_configs'] (reference fleet spelling)
            pipe_cfg = getattr(strategy, "pipeline_configs", None)
            if isinstance(pipe_cfg, dict) and "accumulate_steps" in pipe_cfg:
                acc = pipe_cfg["accumulate_steps"]
            pp_cfg = getattr(strategy, "hybrid_configs", {}).get("pp_configs", None)
            if pp_cfg is not None:
                acc = getattr(pp_cfg, "accumulate_steps", None) or (
                    pp_cfg.get("accumulate_steps", acc) if isinstance(pp_cfg, dict) else acc
                )
        self.accumulate_steps = int(acc)
        self.num_stages = layers.get_num_stages()
        self.stage_id = 0  # single-controller: every process sees all stages
        self.total_loss: Optional[Tensor] = None

    # reference API parity helpers
    def is_pipeline_first_stage(self) -> bool:
        return True

    def is_pipeline_last_stage(self) -> bool:
        return True

    def forward(self, x: Any) -> Any:
        return self._layers(x)

    def _forward_step(self, micro: Any) -> Tensor:
        if isinstance(micro, (tuple, list)) and self._layers._loss_fn is not None:
            inputs, labels = micro[0], micro[1]
            out = self._layers(inputs)
            loss = self._layers._loss_fn(out, labels)
        else:
            out = self._layers(micro)
            loss = out
        return loss

    def forward_backward_pipeline(
        self, data: Any, scaler: Any = None, static_scheduler: bool = False
    ) -> Tensor:
        """Run all microbatches fwd+bwd, accumulating grads — the 1F1B
        numerics (reference ``:547``). Returns the mean microbatch loss."""
        micros = _split_micro(data, self.accumulate_steps)
        total: Optional[Tensor] = None
        inv = 1.0 / float(self.accumulate_steps)
        for micro in micros:
            loss = self._forward_step(micro)
            scaled = loss * inv
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss.detach() if total is None else total + loss.detach()
        self.total_loss = total * inv
        return self.total_loss

    def train_batch(
        self,
        data: Any,
        optimizer: Any,
        lr_scheduler: Any = None,
        scaler: Any = None,
    ) -> Tensor:
        self.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data: Any, compute_loss: bool = True) -> Tensor:
        self.eval()
        import paddle_tpu

        micros = _split_micro(data, self.accumulate_steps)
        with paddle_tpu.no_grad():
            if compute_loss:
                total: Optional[Tensor] = None
                for micro in micros:
                    loss = self._forward_step(micro)
                    total = loss if total is None else total + loss
                return total * (1.0 / self.accumulate_steps)
            # no loss: return the full batch's outputs, microbatches re-joined
            from paddle_tpu.ops.manipulation import concat

            outs = []
            for micro in micros:
                inp = micro[0] if isinstance(micro, (tuple, list)) else micro
                outs.append(self._layers(inp))
            return concat(outs, axis=0)


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-pipeline (VPP) schedule (reference ``:1138``). Numerically
    identical to 1F1B; the virtual-stage segmentation lives in
    ``PipelineLayer(num_virtual_pipeline_stages=...)`` and the overlap comes
    from the SPMD executor, so this wrapper only validates configuration."""

    def __init__(self, layers: Any, hcg: Any = None, strategy: Any = None) -> None:
        super().__init__(layers, hcg=hcg, strategy=strategy)
        if layers._num_virtual_pipeline_stages < 2:
            raise ValueError(
                "PipelineParallelWithInterleave needs PipelineLayer("
                "num_virtual_pipeline_stages >= 2)"
            )
