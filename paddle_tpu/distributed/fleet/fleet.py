"""Fleet facade (reference ``fleet/fleet.py`` ``init:218``,
``distributed_model`` dispatch ``fleet/model.py:133-175``,
``distributed_optimizer:1427``)."""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.distributed.fleet.base.distributed_strategy import DistributedStrategy
from paddle_tpu.distributed.fleet.base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
)

_hcg: Optional[HybridCommunicateGroup] = None
_strategy: Optional[DistributedStrategy] = None


def init(
    role_maker: Any = None,
    is_collective: bool = True,
    strategy: Optional[DistributedStrategy] = None,
) -> None:
    """Build the hybrid topology from strategy.hybrid_configs and set the
    global mesh (reference builds HybridCommunicateGroup + NCCL groups; here
    one ProcessMesh + axis-named groups)."""
    global _hcg, _strategy
    _strategy = strategy or DistributedStrategy()
    hc = _strategy.hybrid_configs
    order = hc.get("order", ["dp", "pp", "sharding", "sep", "mp"])
    name_map = {"dp": "data", "pp": "pipe", "sharding": "sharding", "sep": "sep", "mp": "model"}
    degree_map = {
        "dp": hc.get("dp_degree", 1),
        "pp": hc.get("pp_degree", 1),
        "sharding": hc.get("sharding_degree", 1),
        "sep": hc.get("sep_degree", 1),
        "mp": hc.get("mp_degree", 1),
    }
    topo = CommunicateTopology(
        hybrid_group_names=[name_map[o] for o in order],
        dims=[degree_map[o] for o in order],
    )
    _hcg = HybridCommunicateGroup(topo)


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def distributed_model(model: Any) -> Any:
    """Wrap by parallel mode (reference ``fleet/model.py:32``). With SPMD
    shardings most wrapping is unnecessary; DP input sharding is applied when
    dp_degree > 1 and no other parallelism needs model code cooperation."""
    if _hcg is None:
        init()
    from paddle_tpu.distributed.parallel import DataParallel

    if _hcg.get_pipe_parallel_world_size() > 1:
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
            PipelineLayer,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            PipelineParallel,
        )

        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg=_hcg, strategy=_strategy)
        return model
    if (
        _hcg.get_data_parallel_world_size() > 1
        and _hcg.get_model_parallel_world_size() == 1
    ):
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer: Any, strategy: Optional[DistributedStrategy] = None) -> Any:
    """Hybrid-parallel optimizer wrap (reference ``fleet.py:1427`` →
    HybridParallelOptimizer): ZeRO-sharded state when a sharding axis exists."""
    if _hcg is not None and _hcg.get_sharding_parallel_world_size() > 1:
        from paddle_tpu.distributed.fleet.meta_optimizers import HybridParallelOptimizer

        return HybridParallelOptimizer(optimizer, hcg=_hcg, strategy=strategy)
    return optimizer


class fleet_worker_utils:  # pragma: no cover - namespace stub for scripts
    pass


def worker_index() -> int:
    from paddle_tpu.distributed.parallel import get_rank

    return get_rank()


def worker_num() -> int:
    from paddle_tpu.distributed.parallel import get_world_size

    return get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0
