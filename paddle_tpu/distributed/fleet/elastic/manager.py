"""Elastic training: membership tracking + scale-change detection.

Reference: ``python/paddle/distributed/fleet/elastic/manager.py`` — an
etcd-backed registry of alive hosts (lease TTL ``:251``), ``PADDLE_ELASTIC_*``
env config (``:128-175``), membership watch, and relaunch hooks.

TPU translation: the registry is the native TCPStore (the same rendezvous
store bootstrap uses — no etcd dependency): each worker renews a heartbeat
key ``elastic/{generation}/beat/{rank}``; the manager scans heartbeats and reports
dead/alive membership. Relaunch is the launcher's job (see
``launch/main.py`` ``--max_restarts``): on failure it re-execs the worker
with ``PADDLE_RESTART_COUNT`` bumped, and the training script resumes from
its latest checkpoint (``paddle_tpu.distributed.checkpoint``).
"""

from __future__ import annotations

import os
import threading
import time
from enum import Enum
from typing import Any, Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus(Enum):
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"  # membership stable
    RESTART = "restart"  # membership changed -> relaunch needed
    EXIT = "exit"


class ElasticManager:
    """Worker membership over a TCPStore (reference manager.py:128-251).

    Env parity (reference ``PADDLE_ELASTIC_*``):
      - ``PADDLE_ELASTIC_TIMEOUT``   heartbeat TTL seconds (default 30)
      - ``PADDLE_ELASTIC_NP``        expected world size
    """

    def __init__(
        self,
        store: Any,
        rank: int,
        world_size: Optional[int] = None,
        ttl: Optional[float] = None,
    ) -> None:
        self._store = store
        self.rank = int(rank)
        # PADDLE_ELASTIC_NP accepts "N" or a "min:max" range (reference
        # manager.py:128-175) — the range is the scale-in/out envelope
        np_env = str(
            world_size
            if world_size is not None
            else os.environ.get("PADDLE_ELASTIC_NP", os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        )
        if ":" in np_env:
            lo, hi = np_env.split(":", 1)
            self.min_np, self.max_np = int(lo), int(hi)
            self.world_size = self.max_np
        else:
            self.world_size = int(np_env)
            self.min_np = self.max_np = self.world_size
        self.ttl = float(
            ttl if ttl is not None else os.environ.get("PADDLE_ELASTIC_TIMEOUT", "30")
        )
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        # membership generation: every rebuild bumps it, which NAMESPACES the
        # beat/fault keys — stale leases and faults from a previous topology
        # can never poison the new one
        self._gen = self._read_gen()

    def _key_absent(self, key: str) -> bool:
        """Non-blocking absence probe for SCAN paths. ``store.get`` has
        rendezvous semantics — a missing key blocks the full store timeout
        waiting to appear — so a liveness scan over per-rank keys would
        stall ``timeout x dead_ranks`` per sweep (minutes with production
        timeouts) if it went through ``get``. Stores without ``check``
        (non-TCPStore duck types) fall back to the blocking read."""
        check = getattr(self._store, "check", None)
        if check is None:
            return False
        try:
            return not check(key)
        except Exception:  # probe failure: fall through to the blocking read
            return False

    def _read_gen(self) -> int:
        try:
            if self._key_absent("elastic/generation"):
                return 0
            return int(self._store.get("elastic/generation").decode())
        except Exception:  # no generation published yet (fresh store) / store down
            return 0

    def _beat_key(self, rank: int) -> str:
        return f"elastic/{self._gen}/beat/{rank}"

    def _fault_key(self, rank: int) -> str:
        return f"elastic/{self._gen}/fault/{rank}"

    # -- worker side --------------------------------------------------------
    def register(self) -> None:
        """Announce membership and start renewing the heartbeat lease. A
        relaunched worker re-registers under the current generation with a
        clean fault state."""
        self._gen = self._read_gen()
        self._store.set(self._fault_key(self.rank), b"")  # clear any old fault
        self._beat()
        self._beat_thread = threading.Thread(target=self._beat_loop, daemon=True)
        self._beat_thread.start()

    def _beat(self) -> None:
        self._store.set(self._beat_key(self.rank), str(time.time()).encode())

    def _beat_loop(self) -> None:
        # renew at 1/3 TTL like a lease keepalive
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._beat()
            except Exception:  # store gone: stop beating, manager sees lease expire
                return

    def stop(self) -> None:
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2)

    # -- fault reporting (per-trainer watchdog integration) ------------------
    def report_fault(self, reason: str = "watchdog") -> None:
        """Mark THIS worker unhealthy (e.g. from a CommWatchdog on_timeout
        hook): the manager treats faulted workers as dead even while their
        heartbeat thread keeps renewing (a hung collective doesn't stop the
        beat thread — the reference integrates CommTaskManager the same way).
        The mark lives in the current generation only; re-register clears it."""
        self._store.set(
            self._fault_key(self.rank), f"{time.time()}|{reason}".encode()
        )

    def watchdog_hook(self) -> Any:
        """``on_timeout`` callable for :class:`CommWatchdog`."""

        def hook(dump: Dict[str, Any]) -> None:
            try:
                self.report_fault(f"hang in {dump.get('section')}")
            # analysis: disable=EH402 best-effort fault mark from a watchdog thread; the store may be gone with the job
            except Exception:  # noqa: BLE001 - store may be gone too
                pass

        return hook

    def _faulted(self, r: int) -> bool:
        try:
            if self._key_absent(self._fault_key(r)):
                return False
            return bool(self._store.get(self._fault_key(r)))
        except Exception:  # missing key / store error both mean "no fault mark"
            return False

    # -- manager side -------------------------------------------------------
    def alive_workers(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.max_np):
            try:
                if self._key_absent(self._beat_key(r)):
                    continue  # never registered (or prior topology): not alive
                raw = self._store.get(self._beat_key(r))
                if now - float(raw.decode()) > self.ttl:
                    continue
            except Exception:  # no beat key / unparsable beat: rank is not alive
                continue
            # fault lookup only for fresh-beat ranks (halves store traffic in
            # the all-healthy case; dead ranks need no fault check)
            if self._faulted(r):
                continue
            alive.append(r)
        return alive

    def watch(self) -> ElasticStatus:
        """One membership scan (reference watch loop):

        - every expected worker alive → HOLD
        - alive count within [min_np, world) or grew past world → RESTART
          (scale-in/out: the job relaunches on the new membership)
        - alive count below min_np → ERROR (cannot make progress)
        """
        alive = self.alive_workers()
        if len(alive) == self.world_size:
            return ElasticStatus.HOLD
        if self.min_np < self.max_np and len(alive) < self.min_np:
            # elastic range: below the viable envelope the job cannot make
            # progress at any permitted scale
            return ElasticStatus.ERROR
        # fixed np (or still within range): relaunch — dead workers respawn at
        # the same scale, or the group rebuilds on the surviving membership
        return ElasticStatus.RESTART

    def dead_workers(self) -> List[int]:
        alive = set(self.alive_workers())
        return [r for r in range(self.max_np) if r not in alive]

    # -- membership-change rebuild ------------------------------------------
    def rebuild_endpoints(self) -> Dict[str, Any]:
        """Compute the post-change topology (reference: the manager rewrites
        ``PADDLE_TRAINER_ENDPOINTS`` before relaunch): survivors get dense new
        ranks in old-rank order; the new world size and a bumped generation
        are published to the store so every relaunched worker agrees."""
        alive = self.alive_workers()
        mapping = {old: new for new, old in enumerate(sorted(alive))}
        old_gen = self._read_gen()
        gen = old_gen + 1
        self._store.set("elastic/generation", str(gen).encode())
        self._store.set(
            "elastic/world",
            ",".join(str(r) for r in sorted(alive)).encode(),
        )
        # the bump invalidates every beat/fault key of the old topology —
        # and must also GC them: each generation writes up to 2*max_np keys,
        # so without deletes the store grows by a full topology per restart
        # for the life of the job. Best-effort: a store without delete (or
        # one tearing down mid-rebuild) only costs the bounded leak back.
        if hasattr(self._store, "delete"):
            for r in range(self.max_np):
                try:
                    self._store.delete(f"elastic/{old_gen}/beat/{r}")
                    self._store.delete(f"elastic/{old_gen}/fault/{r}")
                except Exception:  # store down: the relaunch path handles it
                    break
        self._gen = gen
        self.world_size = len(alive)
        return {
            "generation": gen,
            "world_size": len(alive),
            "rank_map": mapping,
            "my_rank": mapping.get(self.rank),  # None when this worker died
        }

    @staticmethod
    def load_topology(store: Any) -> Optional[Dict[str, Any]]:
        """Worker side after relaunch: read the published membership."""
        try:
            check = getattr(store, "check", None)
            if check is not None and not check("elastic/generation"):
                return None  # not published: answer now, don't rendezvous
            gen = int(store.get("elastic/generation").decode())
            world = [int(r) for r in store.get("elastic/world").decode().split(",") if r]
        except Exception:  # topology not published (yet): caller falls back to static launch
            return None
        return {"generation": gen, "world_size": len(world), "members": world}
