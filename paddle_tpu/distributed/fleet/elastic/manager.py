"""Elastic training: membership tracking + scale-change detection.

Reference: ``python/paddle/distributed/fleet/elastic/manager.py`` — an
etcd-backed registry of alive hosts (lease TTL ``:251``), ``PADDLE_ELASTIC_*``
env config (``:128-175``), membership watch, and relaunch hooks.

TPU translation: the registry is the native TCPStore (the same rendezvous
store bootstrap uses — no etcd dependency): each worker renews a heartbeat
key ``elastic/beat/{rank}``; the manager scans heartbeats and reports
dead/alive membership. Relaunch is the launcher's job (see
``launch/main.py`` ``--max_restarts``): on failure it re-execs the worker
with ``PADDLE_RESTART_COUNT`` bumped, and the training script resumes from
its latest checkpoint (``paddle_tpu.distributed.checkpoint``).
"""

from __future__ import annotations

import os
import threading
import time
from enum import Enum
from typing import Any, Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus(Enum):
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"  # membership stable
    RESTART = "restart"  # membership changed -> relaunch needed
    EXIT = "exit"


class ElasticManager:
    """Worker membership over a TCPStore (reference manager.py:128-251).

    Env parity (reference ``PADDLE_ELASTIC_*``):
      - ``PADDLE_ELASTIC_TIMEOUT``   heartbeat TTL seconds (default 30)
      - ``PADDLE_ELASTIC_NP``        expected world size
    """

    def __init__(
        self,
        store: Any,
        rank: int,
        world_size: Optional[int] = None,
        ttl: Optional[float] = None,
    ) -> None:
        self._store = store
        self.rank = int(rank)
        self.world_size = int(
            world_size
            if world_size is not None
            else os.environ.get("PADDLE_ELASTIC_NP", os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        )
        self.ttl = float(
            ttl if ttl is not None else os.environ.get("PADDLE_ELASTIC_TIMEOUT", "30")
        )
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None

    # -- worker side --------------------------------------------------------
    def register(self) -> None:
        """Announce membership and start renewing the heartbeat lease."""
        self._beat()
        self._beat_thread = threading.Thread(target=self._beat_loop, daemon=True)
        self._beat_thread.start()

    def _beat(self) -> None:
        self._store.set(f"elastic/beat/{self.rank}", str(time.time()).encode())

    def _beat_loop(self) -> None:
        # renew at 1/3 TTL like a lease keepalive
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._beat()
            except Exception:
                return  # store gone: the manager will see the lease expire

    def stop(self) -> None:
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2)

    # -- manager side -------------------------------------------------------
    def alive_workers(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.world_size):
            try:
                raw = self._store.get(f"elastic/beat/{r}")
                if now - float(raw.decode()) <= self.ttl:
                    alive.append(r)
            except Exception:
                continue
        return alive

    def watch(self) -> ElasticStatus:
        """One membership scan (reference watch loop): HOLD when everyone is
        alive, RESTART when membership shrank (dead heartbeat)."""
        alive = self.alive_workers()
        if len(alive) == self.world_size:
            return ElasticStatus.HOLD
        return ElasticStatus.RESTART

    def dead_workers(self) -> List[int]:
        alive = set(self.alive_workers())
        return [r for r in range(self.world_size) if r not in alive]
