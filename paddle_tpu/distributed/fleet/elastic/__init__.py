from paddle_tpu.distributed.fleet.elastic.manager import (  # noqa: F401
    ElasticManager,
    ElasticStatus,
)
