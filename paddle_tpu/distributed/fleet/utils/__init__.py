from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils  # noqa: F401
from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (  # noqa: F401
    AllGatherOp,
    GatherOp,
    ReduceScatterOp,
    ScatterOp,
    register_sequence_parallel_allreduce_hooks,
)
