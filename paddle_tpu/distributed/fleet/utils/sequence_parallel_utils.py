"""Megatron-style sequence parallelism (SP).

Reference: ``python/paddle/distributed/fleet/utils/sequence_parallel_utils.py``
(``ScatterOp:85``, ``GatherOp:97``, ``AllGatherOp:111``, ``ReduceScatterOp:127``,
``ColumnSequenceParallelLinear:427``, ``RowSequenceParallelLinear``,
``register_sequence_parallel_allreduce_hooks:192``).

TPU-native: SP is *sequence-dimension sharding over the mp axis*. The
reference's four PyLayers are the manual collective schedule around TP blocks
(scatter seq → TP region → gather seq); under GSPMD the same schedule falls out
of constraining the sequence dim sharded outside TP blocks and letting XLA
place the all-gather/reduce-scatter on ICI. Inside ``shard_map`` regions the
ops lower to explicit ``lax`` collectives with the reference's exact
forward/backward duals.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from paddle_tpu.core.dispatch import defop
from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import (
    _axis_in_trace,
    _get_mp_env,
    _lax_axis_size,
)
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "ScatterOp",
    "GatherOp",
    "AllGatherOp",
    "ReduceScatterOp",
    "scatter",
    "all_gather",
    "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear",
]

_SEQ_DIM = 0  # reference keeps [s, b, h] layout inside SP regions


def _check_divisible(n: int, world: int, what: str) -> None:
    if n % world != 0:
        raise ValueError(f"{what}: sequence dim {n} not divisible by mp world size {world}")


@defop("sp_scatter")
def _scatter_op(x: Any, *, axis: str) -> Any:
    # fwd: keep own seq chunk; bwd: all-gather seq (GatherOp's forward)
    @jax.custom_vjp
    def f(v):
        world = _lax_axis_size(axis)
        _check_divisible(v.shape[_SEQ_DIM], world, "ScatterOp")
        idx = jax.lax.axis_index(axis)
        d = v.shape[_SEQ_DIM] // world
        return jax.lax.dynamic_slice_in_dim(v, idx * d, d, axis=_SEQ_DIM)

    def fwd(v):
        return f(v), None

    def bwd(_, g):
        return (jax.lax.all_gather(g, axis, axis=_SEQ_DIM, tiled=True),)

    f.defvjp(fwd, bwd)
    return f(x)


@defop("sp_gather")
def _gather_op(x: Any, *, axis: str) -> Any:
    # fwd: all-gather seq; bwd: slice own seq chunk (ScatterOp's forward) —
    # the dual for a *replicated* downstream gradient (reference GatherOp)
    @jax.custom_vjp
    def f(v):
        return jax.lax.all_gather(v, axis, axis=_SEQ_DIM, tiled=True)

    def fwd(v):
        return f(v), v.shape[_SEQ_DIM]

    def bwd(d, g):
        idx = jax.lax.axis_index(axis)
        return (jax.lax.dynamic_slice_in_dim(g, idx * d, d, axis=_SEQ_DIM),)

    f.defvjp(fwd, bwd)
    return f(x)


@defop("sp_all_gather")
def _all_gather_op(x: Any, *, axis: str) -> Any:
    # fwd: all-gather seq; bwd: reduce-scatter seq (ReduceScatterOp forward) —
    # the dual for per-rank partial downstream gradients (reference AllGatherOp)
    @jax.custom_vjp
    def f(v):
        return jax.lax.all_gather(v, axis, axis=_SEQ_DIM, tiled=True)

    def fwd(v):
        return f(v), None

    def bwd(_, g):
        return (jax.lax.psum_scatter(g, axis, scatter_dimension=_SEQ_DIM, tiled=True),)

    f.defvjp(fwd, bwd)
    return f(x)


@defop("sp_reduce_scatter")
def _reduce_scatter_op(x: Any, *, axis: str) -> Any:
    @jax.custom_vjp
    def f(v):
        return jax.lax.psum_scatter(v, axis, scatter_dimension=_SEQ_DIM, tiled=True)

    def fwd(v):
        return f(v), None

    def bwd(_, g):
        return (jax.lax.all_gather(g, axis, axis=_SEQ_DIM, tiled=True),)

    f.defvjp(fwd, bwd)
    return f(x)


class ScatterOp:
    """Split the sequence dim across the mp group (fwd) / gather (bwd)."""

    @staticmethod
    def apply(x: Any, group: Any = None) -> Any:
        mesh, axis, world = _get_mp_env(group)
        if world == 1:
            return x
        if _axis_in_trace(axis):
            return _scatter_op(x, axis=axis)
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import mark_sharded

        return mark_sharded(x, _SEQ_DIM, group)


class GatherOp:
    """Gather the sequence dim (fwd) / slice grads (bwd, replicated-grad dual)."""

    @staticmethod
    def apply(x: Any, group: Any = None) -> Any:
        mesh, axis, world = _get_mp_env(group)
        if world == 1:
            return x
        if _axis_in_trace(axis):
            return _gather_op(x, axis=axis)
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import mark_replicated

        return mark_replicated(x, group)


class AllGatherOp:
    """All-gather seq (fwd) / reduce-scatter grads (bwd, partial-grad dual) —
    used before the qkv/up projection in SP attention/mlp blocks."""

    @staticmethod
    def apply(x: Any, group: Any = None) -> Any:
        mesh, axis, world = _get_mp_env(group)
        if world == 1:
            return x
        if _axis_in_trace(axis):
            return _all_gather_op(x, axis=axis)
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import mark_replicated

        return mark_replicated(x, group)


class ReduceScatterOp:
    """Reduce-scatter seq (fwd) / all-gather grads (bwd) — used after the
    out/down projection."""

    @staticmethod
    def apply(x: Any, group: Any = None) -> Any:
        mesh, axis, world = _get_mp_env(group)
        if world == 1:
            return x
        if _axis_in_trace(axis):
            return _reduce_scatter_op(x, axis=axis)
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import mark_sharded

        return mark_sharded(x, _SEQ_DIM, group)


def scatter(x: Any, group: Any = None) -> Any:
    return ScatterOp.apply(x, group)


def all_gather(x: Any, group: Any = None) -> Any:
    return AllGatherOp.apply(x, group)


def mark_as_sequence_parallel_parameter(parameter: Any) -> None:
    """Tag params (layernorm etc.) whose grads need an mp-group allreduce in
    the reference's hook scheme (``:165``). Under GSPMD replicated params
    already receive reduced grads; the tag is kept for API parity/inspection."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter: Any) -> bool:
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model: Any, accumulation_steps: int = 1, fuse_sequence_parallel_allreduce: bool = False) -> None:
    """Reference ``:192``: hooks all-reducing tagged params' grads over mp.

    Global-view: replicated parameters contracted against seq-sharded
    activations already produce fully-reduced grads (XLA inserts the psum), so
    the hooks are no-ops; kept so reference training scripts run unchanged."""
    for p in model.parameters():
        if is_sequence_parallel_parameter(p):
            p.sequence_parallel = True


class ColumnSequenceParallelLinear(Layer):
    """ColumnParallelLinear fused with the SP boundary: input arrives
    seq-sharded, is (all-)gathered, and the matmul output stays column-sharded.
    Reference: ``sequence_parallel_utils.py:427``."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr: Any = None,
        has_bias: bool = True,
        gather_output: bool = False,
        fuse_matmul_bias: bool = False,
        mp_group: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import _shard_param

        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self._group = mp_group
        _, _, self.world_size = _get_mp_env(mp_group)
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features ({out_features}) must be divisible by mp world size ({self.world_size})"
            )
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 1, mp_group)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _shard_param(self.bias, 0, mp_group)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        x = AllGatherOp.apply(x, self._group)
        y = F.linear(x, self.weight, self.bias)
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        if self.gather_output:
            return mp_ops._c_concat(y, self._group)
        return mp_ops.mark_sharded(y, -1, self._group)


class RowSequenceParallelLinear(Layer):
    """RowParallelLinear fused with the SP boundary: the partial-sum output is
    reduce-scattered over the sequence dim instead of all-reduced."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr: Any = None,
        has_bias: bool = True,
        input_is_parallel: bool = True,
        fuse_matmul_bias: bool = False,
        mp_group: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import _shard_param

        self.in_features = in_features
        self.out_features = out_features
        self._group = mp_group
        _, _, self.world_size = _get_mp_env(mp_group)
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features ({in_features}) must be divisible by mp world size ({self.world_size})"
            )
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 0, mp_group)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _shard_param(self.bias, None, mp_group)
            mark_as_sequence_parallel_parameter(self.bias)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        x = mp_ops.mark_sharded(x, -1, self._group)
        y = F.linear(x, self.weight)
        y = ReduceScatterOp.apply(y, self._group)
        if self.bias is not None:
            y = y + self.bias
        return y
