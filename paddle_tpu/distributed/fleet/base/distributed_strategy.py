"""DistributedStrategy (reference ``fleet/base/distributed_strategy.py`` backed
by ``distributed_strategy.proto``). Plain-python config object with the same
field surface; on TPU most toggles select sharding/mesh layouts rather than
NCCL behaviors."""

from __future__ import annotations

from typing import Any, Dict


class DistributedStrategy:
    def __init__(self) -> None:
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0,
            "use_pure_fp16": False,
            "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {"tensor_parallel_degree": 1}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"sharding_degree": 1, "stage": 1}
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        self.lamb = False
        self.dgc = False
        self.find_unused_parameters = False
        self.gradient_scale_configs: Dict[str, Any] = {"scale_strategy": "avg"}

    def __repr__(self) -> str:
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
