"""Hybrid-parallel topology (reference ``fleet/base/topology.py``:
``CommunicateTopology:70``, ``HybridCommunicateGroup:189``).

Builds the nd-mesh over axes [dp, pp, sharding, sep, mp] and exposes per-axis
"communication groups". TPU-native: each axis IS a mesh dimension of one
``ProcessMesh``; a Group carries the axis name so collectives inside shard_map
regions bind to the right ICI ring — no per-axis NCCL communicator creation.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.distributed.collective import Group, new_group
from paddle_tpu.distributed.mesh import ProcessMesh, set_mesh


class CommunicateTopology:
    def __init__(
        self,
        hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "sep", "model"),
        dims: Sequence[int] = (1, 1, 1, 1, 1),
    ) -> None:
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = itertools.product(*[range(d) for d in self._dims])
        self._coord_map: Dict[Tuple[int, ...], int] = {}
        self._rank_map: Dict[int, Tuple[int, ...]] = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self._dims])):
            self._coord_map[coord] = rank
            self._rank_map[rank] = coord

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **args: int) -> int:
        coord = tuple(args[name] for name in self._parallel_names)
        return self._coord_map[coord]

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self._rank_map[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for coord, r in self._coord_map.items() if coord[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along axis_name: ranks varying along that axis only."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [range(d) for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for other_coord in itertools.product(*other_dims):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(other_coord)
                coord.insert(axis, i)
                ranks.append(self._coord_map[tuple(coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank: int, **kwargs: int) -> int:
        coord = list(self.get_coord(global_rank))
        for name, value in kwargs.items():
            coord[self._parallel_names.index(name)] = value
        return self._coord_map[tuple(coord)]


class HybridCommunicateGroup:
    """Per-axis groups + the global ProcessMesh for SPMD lowering."""

    def __init__(self, topology: CommunicateTopology) -> None:
        self._topo = topology
        self.global_rank = 0
        self._dp_degree = self._topo.get_dim("data")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._sep_degree = self._topo.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = self._topo.get_dim("model")
        # the single SPMD mesh: axis order mirrors the reference's topology
        names_map = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}
        dims, names = [], []
        for name in self._topo.get_hybrid_group_names():
            dims.append(self._topo.get_dim(name))
            names.append(names_map.get(name, name))
        self._mesh = ProcessMesh(shape=dims, dim_names=names, process_ids=list(range(int(np.prod(dims)))))
        set_mesh(self._mesh)
        self._dp_group = new_group(self._topo.get_comm_list("data")[0], axis_name="dp")
        self._pp_group = new_group(self._topo.get_comm_list("pipe")[0], axis_name="pp")
        self._sharding_group = new_group(self._topo.get_comm_list("sharding")[0], axis_name="sharding")
        self._mp_group = new_group(self._topo.get_comm_list("model")[0], axis_name="mp")
        self._sep_group = (
            new_group(self._topo.get_comm_list("sep")[0], axis_name="sep") if self._sep_degree > 1 else None
        )

    @property
    def topology(self) -> CommunicateTopology:
        return self._topo

    def get_parallel_mesh(self) -> ProcessMesh:
        return self._mesh

    # data parallel
    def get_data_parallel_rank(self) -> int:
        return 0

    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_data_parallel_group(self) -> Group:
        return self._dp_group

    def get_data_parallel_group_src_rank(self) -> int:
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self) -> int:
        return 0

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_model_parallel_group(self) -> Group:
        return self._mp_group

    def get_model_parallel_group_src_rank(self) -> int:
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self) -> int:
        return 0

    def get_pipe_parallel_rank(self) -> int:
        return 0

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_pipe_parallel_group(self) -> Group:
        return self._pp_group

    def get_p2p_groups(self) -> Any:
        return None

    # sharding
    def get_sharding_parallel_rank(self) -> int:
        return 0

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> Group:
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self) -> int:
        return self._sharding_group.ranks[0]

    # sep
    def get_sep_parallel_rank(self) -> int:
        return 0

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    def get_sep_parallel_group(self) -> Optional[Group]:
        return self._sep_group

    def get_check_parallel_group(self, sharding: bool = False) -> Group:
        return self._mp_group

    def get_rank_from_stage(self, stage_id: int, **kwargs: int) -> int:
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)
