"""Tensor (model) parallel layers.

Reference: ``python/paddle/distributed/fleet/layers/mpu/mp_layers.py``
(``VocabParallelEmbedding:47``, ``ColumnParallelLinear:334``,
``RowParallelLinear:541``, ``ParallelCrossEntropy:742``).

TPU-native design: the reference allocates *per-rank slices* of each weight and
wires NCCL collectives by hand; here each layer owns the **global** parameter
placed with a NamedSharding over the 'mp' mesh axis, and forward computes on
global-view arrays — XLA/GSPMD partitions the matmuls onto the MXU and inserts
the all-reduce/all-gather on ICI exactly where the reference calls
``_mp_allreduce``/``_c_concat``. The same layer code therefore works in eager,
under ``paddle_tpu.jit``, and in multi-host SPMD without modification.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.distributed.fleet.layers.mpu import mp_ops
from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import _get_mp_env
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelCrossEntropy",
]


def _shard_param(param: Any, dim: Optional[int], group: Any = None) -> None:
    """Place a parameter over the mesh: Shard(dim) on the mp axis (dim=None →
    replicated). In-place on the Parameter's buffer, outside the grad tape."""
    mesh, axis, world = _get_mp_env(group)
    if world == 1 or mesh is None:
        return
    from paddle_tpu.distributed.api import shard_tensor
    from paddle_tpu.distributed.placements import Replicate, Shard

    placements = []
    for name in mesh.dim_names:
        if name == axis and dim is not None:
            placements.append(Shard(dim))
        else:
            placements.append(Replicate())
    import paddle_tpu

    with paddle_tpu.no_grad():
        d = shard_tensor(param, mesh, placements)
    param._data = d._data
    param.process_mesh = mesh
    param.placements = placements


class VocabParallelEmbedding(Layer):
    """Embedding with the vocabulary dimension sharded over the mp axis.

    The reference masks out-of-range ids per rank and all-reduces the partial
    lookups (``mp_layers.py:47``); GSPMD derives the identical masked-gather +
    psum from the row-sharded table.
    """

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        weight_attr: Any = None,
        mp_group: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self._group = mp_group
        _, _, self.world_size = _get_mp_env(mp_group)
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError(
                f"num_embeddings ({num_embeddings}) must be divisible by mp world size ({self.world_size})"
            )
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr)
        _shard_param(self.weight, 0, mp_group)

    def forward(self, x: Any) -> Any:
        out = F.embedding(x, self.weight)
        # constrain back to replicated: the partial-lookup psum point
        return mp_ops.mark_replicated(out, self._group)

    def extra_repr(self) -> str:
        return f"num_embeddings={self.num_embeddings}, embedding_dim={self.embedding_dim}, mp={self.world_size}"


class ColumnParallelLinear(Layer):
    """Linear with the output (column) dimension sharded over the mp axis.

    ``gather_output=True`` constrains the result back to replicated (the
    reference's ``_c_concat``); ``False`` leaves it column-sharded for a
    following RowParallelLinear (the Megatron pattern).
    Reference: ``mp_layers.py:334``.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr: Any = None,
        has_bias: bool = True,
        gather_output: bool = True,
        fuse_matmul_bias: bool = False,
        mp_group: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self._group = mp_group
        _, _, self.world_size = _get_mp_env(mp_group)
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features ({out_features}) must be divisible by mp world size ({self.world_size})"
            )
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 1, mp_group)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _shard_param(self.bias, 0, mp_group)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        # grads of a replicated x against a column-sharded W are partial over
        # mp — XLA emits the allreduce the reference codes as _c_identity.
        x = mp_ops._c_identity(x, self._group)
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return mp_ops._c_concat(y, self._group)
        return mp_ops.mark_sharded(y, -1, self._group)

    def extra_repr(self) -> str:
        return f"in={self.in_features}, out={self.out_features}, gather_output={self.gather_output}, mp={self.world_size}"


class RowParallelLinear(Layer):
    """Linear with the input (row) dimension sharded over the mp axis.

    With ``input_is_parallel=True`` the incoming activation is already
    column-sharded (from a ColumnParallelLinear); the matmul produces partial
    sums that XLA reduces over mp (the reference's ``_mp_allreduce``). Bias is
    added after the reduction. Reference: ``mp_layers.py:541``.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr: Any = None,
        has_bias: bool = True,
        input_is_parallel: bool = False,
        fuse_matmul_bias: bool = False,
        mp_group: Any = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self._group = mp_group
        _, _, self.world_size = _get_mp_env(mp_group)
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features ({in_features}) must be divisible by mp world size ({self.world_size})"
            )
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 0, mp_group)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _shard_param(self.bias, None, mp_group)
        else:
            self.bias = None

    def forward(self, x: Any) -> Any:
        if not self.input_is_parallel:
            x = mp_ops._c_split(x, self._group)
        else:
            x = mp_ops.mark_sharded(x, -1, self._group)
        y = F.linear(x, self.weight)
        y = mp_ops._mp_allreduce(y, self._group)
        if self.bias is not None:
            y = y + self.bias
        return y

    def extra_repr(self) -> str:
        return f"in={self.in_features}, out={self.out_features}, input_is_parallel={self.input_is_parallel}, mp={self.world_size}"


class ParallelCrossEntropy(Layer):
    """Softmax cross entropy over class-dim-sharded logits.

    The reference computes per-rank max/sum partials and all-reduces them
    (``mp_layers.py:742``); GSPMD derives the same two reductions from the
    sharding of the class dimension, so this is the stock loss on a constrained
    layout.
    """

    def __init__(self, mp_group: Any = None, name: Optional[str] = None, ignore_index: int = -100) -> None:
        super().__init__()
        self._group = mp_group
        self.ignore_index = ignore_index

    def forward(self, input: Any, label: Any) -> Any:  # noqa: A002
        logits = mp_ops.mark_sharded(input, -1, self._group)
        return F.softmax_with_cross_entropy(logits, label, ignore_index=self.ignore_index)
