"""Tensor-parallel collective ops.

Reference: ``python/paddle/distributed/fleet/layers/mpu/mp_ops.py``
(``_c_identity``, ``_c_concat``, ``_c_split``, ``_mp_allreduce``, …). Those are
hand-placed NCCL calls with custom backward rules; the TPU-native equivalents
are *sharding annotations*: a forward identity whose backward all-reduces is
exactly what GSPMD emits when a replicated activation feeds a sharded matmul,
so in the global-view path these ops become differentiable
``with_sharding_constraint`` placements and XLA inserts the collectives.
Inside a ``shard_map`` region (per-shard view, used by the pipeline runtime and
tests) they lower to explicit ``lax`` collectives with custom VJPs — the same
dual the reference expresses with its PyLayer forward/backward pairs.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.collective import Group
from paddle_tpu.distributed.mesh import get_mesh

__all__ = [
    "_c_identity",
    "_c_concat",
    "_c_split",
    "_mp_allreduce",
    "_get_mp_env",
    "mark_sharded",
    "mark_replicated",
]


def _lax_axis_size(axis: str):
    """``jax.lax.axis_size`` with a jax<0.5 fallback: ``psum(1, axis)``
    constant-folds to the same static size (and raises the same ``NameError``
    for an unbound axis name)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)


def _axis_in_trace(axis: Optional[str]) -> bool:
    """True when `axis` is a bound shard_map/pmap axis in the current trace."""
    if axis is None:
        return False
    try:
        _lax_axis_size(axis)
        return True
    except NameError:
        return False


def _get_mp_env(group: Optional[Group] = None):
    """Resolve (mesh, mp_axis_name, world_size) for the model-parallel group.

    Order: explicit group → fleet hybrid group → a mesh axis named 'mp'/'model'.
    """
    axis = group.axis_name if group is not None else None
    if axis is None:
        from paddle_tpu.distributed.fleet import fleet as _fleet

        hcg = _fleet.get_hybrid_communicate_group()
        if hcg is not None and hcg.get_model_parallel_world_size() > 1:
            axis = hcg.get_model_parallel_group().axis_name
    mesh = get_mesh()
    if axis is None and mesh is not None:
        for cand in ("mp", "model", "tp"):
            if cand in mesh.dim_names:
                axis = cand
                break
    if axis is None:
        return None, None, 1
    world = group.nranks if group is not None else mesh.get_dim_size(axis)
    return mesh, axis, world


@defop("sharding_constraint")
def _constrain(x: Any, *, sharding: Any) -> Any:
    # Differentiable placement: under ad-tracing this is the
    # sharding_constraint primitive (transpose = same constraint); on concrete
    # arrays it reshards via device_put.
    return jax.lax.with_sharding_constraint(x, sharding)


def _merged_spec(t: Any, dim: Optional[int], axis: str) -> PartitionSpec:
    """Spec that places `axis` on `dim` (None → nowhere) while PRESERVING the
    tensor's existing placement on every other mesh axis — constraining only
    the mp axis, so dp/batch shardings survive hybrid dp+mp training."""
    ndim = t.ndim
    data = t.data if isinstance(t, Tensor) else t
    current = getattr(data, "sharding", None)
    entries: list = [None] * ndim
    if isinstance(current, NamedSharding):
        spec = list(current.spec) + [None] * (ndim - len(current.spec))
        for i, e in enumerate(spec):
            if not isinstance(e, (str, tuple, list)):
                # None, or jax<0.5's UNCONSTRAINED singleton (not iterable):
                # neither pins this dim to a mesh axis — nothing to preserve
                continue
            kept = tuple(a for a in ((e,) if isinstance(e, str) else tuple(e)) if a != axis)
            entries[i] = kept[0] if len(kept) == 1 else (kept or None)
    else:
        # unknown layout (tracer inside user jit): leave other dims free
        entries = [PartitionSpec.UNCONSTRAINED] * ndim
    if dim is not None:
        entries[dim % ndim] = axis
    return PartitionSpec(*entries)


def mark_sharded(t: Any, dim: int, group: Optional[Group] = None) -> Any:
    """Constrain tensor dim to be sharded over the mp axis (other axes kept)."""
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return t
    sharding = NamedSharding(mesh.jax_mesh(), _merged_spec(t, dim, axis))
    return _constrain(t, sharding=sharding)


def mark_replicated(t: Any, group: Optional[Group] = None) -> Any:
    """Constrain tensor to be replicated over the mp axis (other axes kept)."""
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return t
    sharding = NamedSharding(mesh.jax_mesh(), _merged_spec(t, None, axis))
    return _constrain(t, sharding=sharding)


# -- shard_map-region variants (explicit collectives with custom VJP) ---------


def _identity_fwd_allreduce_bwd(axis: str):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (jax.lax.psum(g, axis),)

    f.defvjp(fwd, bwd)
    return f


def _allreduce_fwd_identity_bwd(axis: str):
    @jax.custom_vjp
    def f(x):
        return jax.lax.psum(x, axis)

    def fwd(x):
        return jax.lax.psum(x, axis), None

    def bwd(_, g):
        return (g,)

    f.defvjp(fwd, bwd)
    return f


@defop("c_identity")
def _c_identity_op(x: Any, *, axis: str) -> Any:
    return _identity_fwd_allreduce_bwd(axis)(x)


@defop("mp_allreduce")
def _mp_allreduce_op(x: Any, *, axis: str) -> Any:
    return _allreduce_fwd_identity_bwd(axis)(x)


@defop("c_concat")
def _c_concat_op(x: Any, *, axis: str) -> Any:
    # gather last dim across the group; bwd = slice out own chunk
    @jax.custom_vjp
    def f(v):
        g = jax.lax.all_gather(v, axis)  # [world, ..., d]
        return jnp.concatenate([g[i] for i in range(g.shape[0])], axis=-1)

    def fwd(v):
        return f(v), v.shape[-1]

    def bwd(d, grad):
        idx = jax.lax.axis_index(axis)
        start = idx * d
        return (jax.lax.dynamic_slice_in_dim(grad, start, d, axis=-1),)

    f.defvjp(fwd, bwd)
    return f(x)


@defop("c_split")
def _c_split_op(x: Any, *, axis: str) -> Any:
    # keep own chunk of last dim; bwd = all_gather
    @jax.custom_vjp
    def f(v):
        world = _lax_axis_size(axis)
        if v.shape[-1] % world != 0:
            raise ValueError(
                f"_c_split: last dim {v.shape[-1]} not divisible by mp world size {world}"
            )
        d = v.shape[-1] // world
        idx = jax.lax.axis_index(axis)
        return jax.lax.dynamic_slice_in_dim(v, idx * d, d, axis=-1)

    def fwd(v):
        return f(v), None

    def bwd(_, grad):
        g = jax.lax.all_gather(grad, axis)
        return (jnp.concatenate([g[i] for i in range(g.shape[0])], axis=-1),)

    f.defvjp(fwd, bwd)
    return f(x)


# -- public PyLayer-parity surface -------------------------------------------


def _c_identity(tensor: Any, group: Optional[Group] = None) -> Any:
    """Forward identity; backward all-reduce over the mp group.

    Global view: identity (GSPMD derives the grad reduction from shardings).
    """
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return tensor
    if _axis_in_trace(axis):
        return _c_identity_op(tensor, axis=axis)
    return tensor


def _mp_allreduce(tensor: Any, group: Optional[Group] = None, use_calc_stream: bool = True, use_model_parallel: bool = True, op: Any = None) -> Any:
    """Forward all-reduce; backward identity.

    Global view: a partial value only arises inside a compiled region, where
    constraining to replicated makes XLA emit the psum.
    """
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return tensor
    if _axis_in_trace(axis):
        return _mp_allreduce_op(tensor, axis=axis)
    return mark_replicated(tensor, group)


def _c_concat(tensor: Any, group: Optional[Group] = None) -> Any:
    """Gather last-dim shards into the full tensor on every rank."""
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return tensor
    if _axis_in_trace(axis):
        return _c_concat_op(tensor, axis=axis)
    return mark_replicated(tensor, group)


def _c_split(tensor: Any, group: Optional[Group] = None) -> Any:
    """Keep this rank's last-dim chunk (inverse of _c_concat)."""
    mesh, axis, world = _get_mp_env(group)
    if world == 1:
        return tensor
    if _axis_in_trace(axis):
        return _c_split_op(tensor, axis=axis)
    return mark_sharded(tensor, -1, group)
