"""TP-group RNG state control.

Reference: ``python/paddle/distributed/fleet/layers/mpu/random.py``
(``RNGStatesTracker``, ``model_parallel_random_seed``, ``get_rng_state_tracker``).
The tracker itself lives in ``paddle_tpu.core.rng`` (a named-Generator registry
over splittable JAX PRNG keys); this module provides the fleet-facing seeding
convention: 'global_seed' shared by all ranks (dropout outside TP regions must
be identical) and 'local_seed' offset per mp rank (dropout on sharded
activations must differ per rank).
"""

from __future__ import annotations

from paddle_tpu.core.rng import RNGStatesTracker, get_rng_state_tracker

__all__ = [
    "RNGStatesTracker",
    "get_rng_state_tracker",
    "model_parallel_random_seed",
    "MODEL_PARALLEL_RNG",
]

MODEL_PARALLEL_RNG = "local_seed"


def model_parallel_random_seed(seed: int = 0) -> None:
    """Install 'global_seed' and 'local_seed' states (reference
    ``random.py`` same-name fn). The local seed is offset by the mp rank so
    per-rank dropout masks decorrelate; under single-controller SPMD the
    process index stands in for the rank (per-shard decorrelation inside a
    compiled region comes from the position-dependent PRNG fold-in)."""
    import jax

    from paddle_tpu.distributed.fleet import fleet as _fleet

    hcg = _fleet.get_hybrid_communicate_group()
    mp_rank = 0
    if hcg is not None:
        mp_rank = hcg.get_model_parallel_rank()
    local_seed = seed + 1024 + mp_rank + jax.process_index() * 4096
    global_seed = seed

    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", global_seed)
    tracker.add(MODEL_PARALLEL_RNG, local_seed)

    import paddle_tpu

    paddle_tpu.seed(global_seed)
