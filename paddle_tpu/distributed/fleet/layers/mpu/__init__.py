from paddle_tpu.distributed.fleet.layers.mpu import mp_ops  # noqa: F401
from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from paddle_tpu.distributed.fleet.layers.mpu.random import (  # noqa: F401
    RNGStatesTracker,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
