"""Fleet facade (reference ``python/paddle/distributed/fleet``)."""

from paddle_tpu.distributed.fleet.base.distributed_strategy import DistributedStrategy  # noqa: F401
from paddle_tpu.distributed.fleet.base.topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
)
from paddle_tpu.distributed.fleet.fleet import (  # noqa: F401
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
)
from paddle_tpu.distributed.fleet.layers.mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from paddle_tpu.distributed.fleet.meta_optimizers import (  # noqa: F401
    DygraphShardingOptimizer,
    DygraphShardingOptimizerV2,
    HybridParallelOptimizer,
)
from paddle_tpu.distributed.fleet.meta_parallel import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
)
from paddle_tpu.distributed.fleet.recompute import (  # noqa: F401
    recompute,
    recompute_sequential,
)
