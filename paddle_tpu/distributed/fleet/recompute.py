"""Activation checkpointing (recompute).

Reference: ``python/paddle/distributed/fleet/recompute/recompute.py`` —
PyLayer-based segment recompute with RNG-state replay. TPU-native mechanics:

- **Eager**: the forward segment runs under ``no_grad`` so no tape residuals
  are held; only the segment *inputs* are saved. Backward re-runs the segment
  with grad recording on, then sweeps the inner tape — parameter grads
  accumulate into ``param.grad`` (additive, so composition with grads arriving
  from outside the segment is correct) and input grads are routed back into
  the outer tape.
- **Under jit capture** the same python runs with tracers, so the recomputed
  ops are traced a second time in the backward region — i.e. the XLA program
  itself contains the rematerialization. ``lax.optimization_barrier`` on the
  saved inputs prevents XLA CSE from collapsing the recomputation back into
  the forward activations (the same guard ``jax.checkpoint`` uses).
- RNG replay: the global generator key is snapshotted at forward and restored
  for the re-run so dropout masks match (reference replays cuda RNG states).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import autograd as _ag
from paddle_tpu.core import rng as _rng
from paddle_tpu.core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def _snapshot_rng() -> Any:
    gen = _rng.default_generator()
    with gen._lock:
        return gen._key


def _restore_rng(key: Any) -> Any:
    gen = _rng.default_generator()
    with gen._lock:
        prev = gen._key
        gen._key = key
    return prev


def recompute(function: Any, *args: Any, **kwargs: Any) -> Any:
    """Run ``function(*args, **kwargs)`` without saving its intermediate
    activations; recompute them during backward.

    ``use_reentrant`` and ``preserve_rng_state`` kwargs are accepted for API
    parity (this implementation is reentrant and always replays RNG).
    """
    kwargs.pop("use_reentrant", None)
    preserve_rng = kwargs.pop("preserve_rng_state", True)

    if not _ag.is_grad_enabled():
        return function(*args, **kwargs)

    # Positional AND keyword tensors are segment inputs (saved, barriered,
    # grads routed back); everything else is replayed by value.
    kw_keys = list(kwargs.keys())
    flat_args: List[Any] = list(args) + [kwargs[k] for k in kw_keys]
    tensor_inputs: List[Tensor] = [
        a for a in flat_args if isinstance(a, Tensor) and not a.stop_gradient
    ]
    rng_key = _snapshot_rng() if preserve_rng else None
    # AMP autocast state must be replayed too: backward may run outside the
    # auto_cast context (reference recompute saves/restores amp state).
    from paddle_tpu.amp.auto_cast import _amp_state, _state as _amp_cfg

    amp_cfg = dict(_amp_cfg())

    with _ag.set_grad_enabled(False):
        outputs = function(*args, **kwargs)

    single = not isinstance(outputs, (list, tuple))
    out_list = [outputs] if single else list(outputs)
    out_tensors = [o for o in out_list if isinstance(o, Tensor)]
    if not out_tensors:
        return outputs
    out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in out_tensors]

    # Save only input *arrays* (device buffers); the python args/kwargs
    # structure is re-assembled at backward time.
    saved_arrays = [a.data if isinstance(a, Tensor) else None for a in flat_args]

    def vjp_fn(cots: Any) -> Tuple[Any, ...]:
        cot_list = [cots] if len(out_avals) == 1 else list(cots)
        # Barrier the saved inputs so XLA cannot CSE the recomputed segment
        # with the original forward (which would keep activations alive).
        barriered = list(saved_arrays)
        arr_idx = [i for i, a in enumerate(barriered) if a is not None]
        if arr_idx:
            fresh = jax.lax.optimization_barrier([barriered[i] for i in arr_idx])
            for i, arr in zip(arr_idx, fresh):
                barriered[i] = arr
        re_flat: List[Any] = []
        recomputed_inputs: List[Tensor] = []
        for a, arr in zip(flat_args, barriered):
            if isinstance(a, Tensor):
                t = Tensor(arr, stop_gradient=a.stop_gradient)
                re_flat.append(t)
                if not a.stop_gradient:
                    recomputed_inputs.append(t)
            else:
                re_flat.append(a)
        re_args = re_flat[: len(args)]
        re_kwargs = dict(zip(kw_keys, re_flat[len(args):]))

        prev_key = _restore_rng(rng_key) if preserve_rng else None
        prev_amp = dict(_amp_cfg())
        _amp_state.cfg = dict(amp_cfg)
        try:
            with _ag.set_grad_enabled(True):
                re_out = function(*re_args, **re_kwargs)
        finally:
            _amp_state.cfg = prev_amp
            if preserve_rng:
                _restore_rng(prev_key)

        re_out_list = [re_out] if not isinstance(re_out, (list, tuple)) else list(re_out)
        re_out_tensors = [o for o in re_out_list if isinstance(o, Tensor)]
        grad_outputs = []
        for c, aval in zip(cot_list, out_avals):
            if c is None or getattr(c, "dtype", None) == jax.dtypes.float0:
                # no upstream grad for this output: seed an explicit zero
                # (run_backward seeds ones for None, which is backward()
                # root semantics, not ours).
                grad_outputs.append(Tensor(jnp.zeros(aval.shape, aval.dtype)))
            else:
                grad_outputs.append(Tensor(c))
        # Inner sweep semantics must match the OUTER sweep's:
        # - under a plain ``backward()`` (no capture set): accumulate mode —
        #   parameter grads write into ``param.grad`` in place, additive, so
        #   composition with grads arriving from outside the segment is
        #   correct (matches the reference PyLayer backward, which calls
        #   paddle.autograd.backward on the inner graph);
        # - under an only-inputs ``autograd.grad()``: inherit the outer
        #   capture set (plus our own segment inputs) so params are NOT
        #   side-effected — unless the caller asked for them.
        # Input grads are read off the fresh leaf tensors afterwards.
        roots: List[Tensor] = []
        root_cots: List[Any] = []
        for o, g in zip(re_out_tensors, grad_outputs):
            if o.grad_node is None and o.stop_gradient:
                continue  # output did not depend on anything differentiable
            roots.append(o)
            root_cots.append(g)
        for t in recomputed_inputs:
            t._grad = None
        outer_capture = _ag.current_grad_capture()
        inner_capture = (
            None
            if outer_capture is None
            else set(outer_capture) | {id(t) for t in recomputed_inputs}
        )
        if roots:
            _ag.run_backward(roots, root_cots, grad_capture=inner_capture)
        out = tuple(
            t.grad.data if t.grad is not None else None
            for t in recomputed_inputs
        )
        return out

    node = _ag.GradNode("recompute", vjp_fn, tensor_inputs, out_avals)
    idx = 0
    wrapped: List[Any] = []
    for o in out_list:
        if isinstance(o, Tensor):
            t = Tensor(o.data, stop_gradient=False)
            t._grad_node = node
            t._grad_output_index = idx
            idx += 1
            wrapped.append(t)
        else:
            wrapped.append(o)
    return wrapped[0] if single else tuple(wrapped)


def recompute_sequential(
    ctx: Optional[dict], functions: Sequence[Any], *args: Any, **kwargs: Any
) -> Any:
    """Recompute a ``Sequential`` (or list of layers) in segments.

    Reference ``recompute_sequential`` — ``ctx`` may carry ``segments`` (int).
    """
    ctx = ctx or {}
    segments = int(ctx.get("segments", 1))
    # kwargs here are recompute-control only (use_reentrant /
    # preserve_rng_state); layer inputs must be positional.
    unknown = set(kwargs) - {"use_reentrant", "preserve_rng_state"}
    if unknown:
        raise TypeError(
            f"recompute_sequential only accepts recompute-control kwargs, got {sorted(unknown)}"
        )
    if hasattr(functions, "children"):
        functions = list(functions.children())
    functions = list(functions)
    if not functions:
        return args[0] if len(args) == 1 else args

    def run_segment(fns: List[Any]):
        def seg(*xs: Any) -> Any:
            out = xs
            for f in fns:
                out = f(*out) if isinstance(out, tuple) else f(out)
            return out

        return seg

    n = len(functions)
    size = max(1, (n + segments - 1) // segments)
    out: Any = args
    for start in range(0, n, size):
        fns = functions[start : start + size]
        if isinstance(out, tuple):
            out = recompute(run_segment(fns), *out, **kwargs)
        else:
            out = recompute(run_segment(fns), out, **kwargs)
    return out
