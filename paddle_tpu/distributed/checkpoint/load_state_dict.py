"""Checkpoint load with reshard-on-load.

Reference: ``python/paddle/distributed/checkpoint/load_state_dict.py:467`` —
reads the metadata manifest, computes the overlap between saved shards and
the shards the *target* tensors need under their (possibly different)
mesh/placements, and transfers the overlapping regions.

TPU-native: assemble each tensor's needed region from the saved shards on
host, then ``jax.device_put`` with the target tensor's sharding — XLA moves
each device's slice; a cross-mesh load (e.g. saved dp2×mp4, loaded dp4×mp2)
is just a different target sharding.
"""

from __future__ import annotations

import glob
import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.checkpoint.metadata import Metadata, file_sha256

__all__ = ["load_state_dict"]


def _read_metadata(path: str) -> List[Metadata]:
    metas = []
    for f in sorted(glob.glob(os.path.join(path, "*.metadata"))):
        with open(f, "rb") as fh:
            metas.append(pickle.load(fh))
    if not metas:
        raise FileNotFoundError(f"no *.metadata manifest under {path}")
    return metas


def _verify_hashes(path: str, metas: List[Metadata]) -> None:
    """Check every manifest-referenced data file against its recorded content
    hash (manifests from before the hash field simply have none). A mismatch
    means a torn/corrupt write — loading it would silently serve garbage."""
    for meta in metas:
        for fname, digest in getattr(meta, "file_hashes", {}).items():
            fp = os.path.join(path, fname)
            if not os.path.isfile(fp):
                raise FileNotFoundError(
                    f"checkpoint payload {fname} referenced by the manifest "
                    f"is missing under {path} (incomplete save?)"
                )
            actual = file_sha256(fp)
            if actual != digest:
                raise ValueError(
                    f"checkpoint payload {fname} failed its content hash "
                    f"({actual[:12]}… != manifest {digest[:12]}…) — torn or "
                    "corrupt write; use CheckpointManager.latest_valid() to "
                    "fall back to the last good checkpoint"
                )


def _assemble(name: str, metas: List[Metadata], payloads: Dict[str, Any]) -> np.ndarray:
    """Reconstruct the global tensor for ``name`` from saved shards."""
    gshape = None
    dtype = None
    pieces = []  # (offset, array)
    for meta in metas:
        if name not in meta.state_dict_metadata:
            continue
        gshape = meta.global_shapes[name]
        for ent in meta.state_dict_metadata[name]:
            key = f"{name}@{ent.global_offset}"
            from paddle_tpu.distributed.checkpoint.metadata import LocalTensorIndex

            storage = meta.storage_metadata.get(LocalTensorIndex(name, ent.global_offset))
            if storage is None:
                continue
            payload = payloads.get(storage)
            if payload is None or key not in payload:
                continue
            data = payload[key]
            dtype = data.dtype
            pieces.append((ent.global_offset, data))
    if gshape is None:
        raise KeyError(f"tensor {name!r} not present in checkpoint")
    if not pieces:
        raise KeyError(f"no shard data found for {name!r} (incomplete checkpoint?)")
    out = np.zeros(gshape, dtype)
    filled = np.zeros(gshape, bool)
    for off, data in pieces:
        sl = tuple(slice(o, o + s) for o, s in zip(off, data.shape))
        out[sl] = data
        filled[sl] = True
    if not filled.all():
        raise ValueError(
            f"checkpoint shards for {name!r} do not cover the full global "
            f"shape {gshape} — a multi-host checkpoint must be loaded with "
            "all its shard files present"
        )
    return out


def load_state_dict(
    state_dict: Dict[str, Any],
    path: str,
    process_group: Any = None,
    coordinator_rank: int = 0,
    unique_id: Optional[int] = None,
    offload: bool = False,
) -> None:
    """Fill ``state_dict``'s tensors in place from the checkpoint at ``path``,
    resharding to each target tensor's current placements."""
    metas = _read_metadata(path)
    _verify_hashes(path, metas)
    npz_files = [np.load(f) for f in glob.glob(os.path.join(path, "*.distcp.npz"))]
    try:
        payloads = {}
        for f, z in zip(glob.glob(os.path.join(path, "*.distcp.npz")), npz_files):
            # read eagerly so the zip handles can be closed after assembly
            payloads[os.path.basename(f)[: -len(".npz")]] = {k: z[k] for k in z.files}
    finally:
        for z in npz_files:
            z.close()

    for name, target in state_dict.items():
        global_np = _assemble(name, metas, payloads)
        if isinstance(target, Tensor):
            sharding = getattr(target._data, "sharding", None)
            if tuple(target.shape) != tuple(global_np.shape):
                raise ValueError(
                    f"shape mismatch for {name!r}: checkpoint {global_np.shape} "
                    f"vs target {tuple(target.shape)}"
                )
            # cast on host; device_put with a sharding places only each
            # device's slice (never materializes the global array on one chip)
            host = global_np.astype(target._data.dtype)
            if sharding is not None and getattr(target._data, "committed", False):
                arr = jax.device_put(host, sharding)  # reshard-on-load
            else:
                # uncommitted target (e.g. a plain buffer): keep it
                # uncommitted so it composes with any mesh downstream
                import jax.numpy as jnp

                arr = jnp.asarray(host)
            target._data = arr
        else:
            state_dict[name] = global_np
