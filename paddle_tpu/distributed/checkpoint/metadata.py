"""Checkpoint metadata: global shape ↔ local shard mapping.

Reference: ``python/paddle/distributed/checkpoint/metadata.py`` —
``LocalTensorMetadata`` (offsets + lengths of one shard in the global
tensor), ``LocalTensorIndex`` (which file holds it), ``Metadata`` (the global
manifest written once by the coordinator).

Crash consistency: the manifest also carries a content hash for every data
file it references (``file_hashes``), written AFTER the data file was
atomically committed — a torn or corrupt payload is detectable instead of
silently loadable, and ``CheckpointManager.latest_valid()`` skips it.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def file_sha256(path: str) -> str:
    """Streaming sha256 of one file (the manifest's content-hash function)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One shard's placement within its global tensor."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Identity of one shard: (tensor name, its global offset)."""

    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    """The manifest: every tensor's global shape/dtype, every shard's
    location, and which data file stores each shard."""

    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(default_factory=dict)
    storage_metadata: Dict[LocalTensorIndex, str] = field(default_factory=dict)
    global_shapes: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)
    # data filename (as written, e.g. "0_0.distcp.npz") -> sha256 hex digest;
    # read with getattr(..., "file_hashes", {}) — manifests pickled before
    # this field existed unpickle without it
    file_hashes: Dict[str, str] = field(default_factory=dict)
