"""Checkpoint metadata: global shape ↔ local shard mapping.

Reference: ``python/paddle/distributed/checkpoint/metadata.py`` —
``LocalTensorMetadata`` (offsets + lengths of one shard in the global
tensor), ``LocalTensorIndex`` (which file holds it), ``Metadata`` (the global
manifest written once by the coordinator).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One shard's placement within its global tensor."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Identity of one shard: (tensor name, its global offset)."""

    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    """The manifest: every tensor's global shape/dtype, every shard's
    location, and which data file stores each shard."""

    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(default_factory=dict)
    storage_metadata: Dict[LocalTensorIndex, str] = field(default_factory=dict)
    global_shapes: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)
