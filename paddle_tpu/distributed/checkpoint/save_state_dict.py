"""Sharded checkpoint save.

Reference: ``python/paddle/distributed/checkpoint/save_state_dict.py:145`` —
each rank writes its local shards to ``{rank}_0.distcp`` and rank 0 writes
the global ``0.metadata`` manifest mapping shard offsets to files.

TPU-native: a global jax.Array already knows its shards
(``arr.addressable_shards`` carries the index of each shard in the global
tensor), so the dist_attr → offsets computation the reference does from
TensorDistAttr falls out of the sharding directly. Multi-host: each process
saves only the shards it addresses; exactly one owner process writes each
shard (the lowest-id device holding it).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.checkpoint.metadata import (
    LocalTensorIndex,
    LocalTensorMetadata,
    Metadata,
    file_sha256,
)
from paddle_tpu.testing.faults import fault_point

__all__ = ["save_state_dict"]


def _atomic_write(path: str, writer) -> None:
    """Write via a sibling tmp file + ``os.replace``: readers never observe a
    half-written file, and a crash mid-write leaves the old file (or nothing)
    instead of a torn one that pickle/npz would happily half-load."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        writer(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _to_array(v: Any):
    if isinstance(v, Tensor):
        return v._data
    return v


def _slice_offsets(idx, shape) -> tuple:
    """Global offsets of a shard from its index (tuple of slices)."""
    out = []
    for sl, dim in zip(idx, shape):
        out.append(int(sl.start) if sl.start is not None else 0)
    return tuple(out)


def save_state_dict(
    state_dict: Dict[str, Any],
    path: str,
    process_group: Any = None,
    coordinator_rank: int = 0,
    unique_id: Optional[int] = None,
) -> None:
    """Write each tensor's local shards + the global metadata manifest."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    uid = 0 if unique_id is None else int(unique_id)
    if rank == coordinator_rank:
        # a checkpoint owns its directory: drop files from an earlier save
        # (possibly with a different rank count) so load never mixes stale
        # shards with fresh ones
        import glob as _glob

        for stale in (
            _glob.glob(os.path.join(path, "*.distcp.npz"))
            + _glob.glob(os.path.join(path, "*.metadata"))
            + _glob.glob(os.path.join(path, "*.tmp"))  # crashed-save leftovers
        ):
            os.remove(stale)
    meta = Metadata()
    shards_payload: Dict[str, np.ndarray] = {}
    fname = f"{rank}_{uid}.distcp"

    for name, value in state_dict.items():
        arr = _to_array(value)
        if not hasattr(arr, "addressable_shards"):
            arr = np.asarray(arr)
            meta.global_shapes[name] = tuple(arr.shape)
            meta.state_dict_metadata[name] = [
                LocalTensorMetadata((0,) * arr.ndim, tuple(arr.shape), str(arr.dtype))
            ]
            key = f"{name}@{(0,) * arr.ndim}"
            meta.storage_metadata[LocalTensorIndex(name, (0,) * arr.ndim)] = fname
            shards_payload[key] = arr
            continue

        gshape = tuple(arr.shape)
        meta.global_shapes[name] = gshape
        entries = []
        seen_offsets = set()
        for shard in arr.addressable_shards:
            off = _slice_offsets(shard.index, gshape)
            if off in seen_offsets:
                continue  # replicated copy: save once
            # multi-host: the shard's owner is the lowest-id device holding
            # this offset; only that process writes it
            if shard.replica_id != 0:
                continue
            seen_offsets.add(off)
            data = np.asarray(shard.data)
            entries.append(LocalTensorMetadata(off, tuple(data.shape), str(data.dtype)))
            meta.storage_metadata[LocalTensorIndex(name, off)] = fname
            shards_payload[f"{name}@{off}"] = data
        meta.state_dict_metadata[name] = entries

    # crash-consistent commit order: (1) data file atomically, (2) hash of
    # the committed bytes into the manifest, (3) manifest atomically — a
    # fault anywhere leaves either no manifest (checkpoint invisible) or a
    # manifest whose hashes expose any missing/torn data file
    fault_point("checkpoint.write")
    payload_path = os.path.join(path, fname + ".npz")
    _atomic_write(payload_path, lambda f: np.savez(f, **shards_payload))
    meta.file_hashes[fname + ".npz"] = file_sha256(payload_path)
    # every process writes its own manifest piece; rank 0's name is canonical.
    # single-host (the common test path): one manifest with everything.
    fault_point("checkpoint.write")
    _atomic_write(
        os.path.join(path, f"{rank}.metadata"), lambda f: pickle.dump(meta, f)
    )
