"""Crash-consistent checkpoint manager: retention, validation, resume.

Reference intent: the fork's elastic stack relaunches a failed job and
resumes "from the checkpoint" — which only works if the checkpoint a crash
left behind is *loadable or detectably bad*, never silently torn. This
manager owns a directory of step-numbered checkpoints:

- :meth:`save` writes into a hidden staging directory and atomically
  ``os.replace``\\ s it into place, so a crash mid-save can never produce a
  half-checkpoint under a committed name;
- :meth:`latest_valid` walks checkpoints newest-first and returns the first
  whose manifest parses AND whose every data file matches its recorded
  content hash — torn/corrupt checkpoints are counted
  (``checkpoints_skipped_torn_total``) and skipped;
- retention keeps the newest ``keep`` checkpoints (older ones are deleted
  only after a save commits, so the invariant "at least one good checkpoint"
  survives a crash at any instant);
- non-array state (an optimizer's LR-scheduler dict, step counters, user
  ``extra``) rides in a JSON sidecar so one :meth:`restore` rebuilds the
  whole training state.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, List, NamedTuple, Optional

from paddle_tpu.distributed.checkpoint.load_state_dict import (
    _read_metadata,
    load_state_dict,
)
from paddle_tpu.distributed.checkpoint.metadata import file_sha256
from paddle_tpu.distributed.checkpoint.save_state_dict import save_state_dict
from paddle_tpu.observability import metrics as _obs

__all__ = ["CheckpointManager", "CheckpointRecord"]

_SIDECAR = "extra_state.json"
_STEP_RE = re.compile(r"^step_(\d{8})$")

_saved_total = _obs.GLOBAL_METRICS.counter(
    "checkpoints_saved_total", "Checkpoints committed by CheckpointManager.save."
)
_skipped_torn_total = _obs.GLOBAL_METRICS.counter(
    "checkpoints_skipped_torn_total",
    "Checkpoints skipped by latest_valid() as torn/corrupt "
    "(unreadable manifest, missing payload, or content-hash mismatch).",
)


class CheckpointRecord(NamedTuple):
    step: int
    path: str


def _is_jsonable(v: Any) -> bool:
    return isinstance(v, (dict, list, tuple, str, bool)) or v is None


class CheckpointManager:
    """Manage ``root/step_XXXXXXXX`` checkpoint directories.

    ``state_dict`` values that are tensors/arrays (anything with ``.shape``)
    or plain numbers go through the sharded array writer; dict/list/str/bool/
    None values go to the JSON sidecar and come back natively from
    :meth:`restore` — so ``{**model_state, **optimizer.state_dict()}`` (which
    mixes tensors, ints and an LR-scheduler dict) round-trips whole.
    """

    def __init__(self, root: str, keep: int = 3) -> None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.root = str(root)
        self.keep = int(keep)
        os.makedirs(self.root, exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def steps(self) -> List[int]:
        """Committed checkpoint steps, ascending (validity not checked)."""
        out = []
        for name in os.listdir(self.root):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save ----------------------------------------------------------------
    def save(
        self,
        state_dict: Dict[str, Any],
        step: int,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Write one checkpoint for ``step``; returns its committed path.

        The whole checkpoint is staged under ``.staging_step_XXXXXXXX`` and
        renamed into place in one ``os.replace`` — an abort at ANY point
        (including an injected ``checkpoint.write`` fault) leaves no
        committed directory, so ``latest_valid()`` still sees the previous
        checkpoint."""
        arrays: Dict[str, Any] = {}
        sidecar_state: Dict[str, Any] = {}
        for k, v in state_dict.items():
            if _is_jsonable(v):
                sidecar_state[k] = v
            else:
                arrays[k] = v  # Tensor / ndarray / scalar — save_state_dict's job
        staging = os.path.join(self.root, f".staging_step_{int(step):08d}")
        shutil.rmtree(staging, ignore_errors=True)
        try:
            save_state_dict(arrays, staging)
            sidecar = {
                "step": int(step),
                "extra": dict(extra or {}),
                "state": sidecar_state,
            }
            payload = json.dumps(sidecar).encode()
            tmp = os.path.join(staging, _SIDECAR + ".tmp")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(staging, _SIDECAR))
            final = self._dir(step)
            trash = None
            if os.path.exists(final):
                # re-save of the same step (a relaunch redoing it): move the
                # old committed checkpoint aside FIRST — os.replace cannot
                # land on a non-empty dir, and rmtree-before-replace would
                # open a crash window with NEITHER checkpoint on disk
                trash = os.path.join(self.root, f".trash_step_{int(step):08d}")
                shutil.rmtree(trash, ignore_errors=True)
                os.replace(final, trash)
            try:
                os.replace(staging, final)
            except BaseException:
                # commit rename failed: put the old checkpoint back so the
                # step is never left with neither version on disk
                if trash is not None:
                    os.replace(trash, final)
                raise
            if trash is not None:
                shutil.rmtree(trash, ignore_errors=True)
        except BaseException:
            # any abort (incl. KeyboardInterrupt / injected fault) must drop
            # the staging dir so no half-written checkpoint can ever commit
            shutil.rmtree(staging, ignore_errors=True)
            raise
        _saved_total.inc()
        self._retain()
        return final

    def _retain(self) -> None:
        for step in self.steps()[: -self.keep]:
            shutil.rmtree(self._dir(step), ignore_errors=True)

    # -- validate / find -----------------------------------------------------
    def validate(self, step: int) -> bool:
        """True iff ``step``'s checkpoint is complete and uncorrupted: the
        manifest parses, every referenced payload exists and matches its
        content hash, and the sidecar (when present) parses."""
        path = self._dir(step)
        try:
            metas = _read_metadata(path)
        except Exception:  # unreadable/missing/torn manifest IS the detected condition
            return False
        for meta in metas:
            hashes = getattr(meta, "file_hashes", {})
            for fname in set(meta.storage_metadata.values()):
                fp = os.path.join(path, fname + ".npz")
                if not os.path.isfile(fp):
                    return False
                digest = hashes.get(fname + ".npz")
                if digest is not None and file_sha256(fp) != digest:
                    return False
        sidecar = os.path.join(path, _SIDECAR)
        if os.path.exists(sidecar):
            try:
                with open(sidecar, "r", encoding="utf-8") as f:
                    json.load(f)
            except (OSError, ValueError):  # torn sidecar: checkpoint unusable
                return False
        return True

    def latest_valid(self) -> Optional[CheckpointRecord]:
        """Newest checkpoint that passes :meth:`validate`; torn ones are
        counted and skipped. None when no valid checkpoint exists."""
        for step in reversed(self.steps()):
            if self.validate(step):
                return CheckpointRecord(step, self._dir(step))
            _skipped_torn_total.inc()
        return None

    # -- restore -------------------------------------------------------------
    def manifest_keys(self, step: int) -> List[str]:
        """Every state key stored at ``step`` (arrays + sidecar)."""
        path = self._dir(step)
        keys = set()
        for meta in _read_metadata(path):
            keys.update(meta.state_dict_metadata)
        keys.update(self._read_sidecar(path)["state"])
        return sorted(keys)

    def _read_sidecar(self, path: str) -> Dict[str, Any]:
        sidecar = os.path.join(path, _SIDECAR)
        if not os.path.exists(sidecar):
            return {"step": -1, "extra": {}, "state": {}}
        with open(sidecar, "r", encoding="utf-8") as f:
            return json.load(f)

    def restore(
        self, state_dict: Dict[str, Any], step: Optional[int] = None
    ) -> Dict[str, Any]:
        """Fill ``state_dict`` from checkpoint ``step`` (default: latest
        valid). Tensor values are filled in place (resharded to their current
        placements); plain-array and sidecar entries are replaced in the
        dict. Returns ``{"step": saved_step, "extra": {...}}``."""
        if step is None:
            rec = self.latest_valid()
            if rec is None:
                raise FileNotFoundError(f"no valid checkpoint under {self.root}")
            step = rec.step
        path = self._dir(step)
        sidecar = self._read_sidecar(path)
        saved_arrays = set()
        for meta in _read_metadata(path):
            saved_arrays.update(meta.state_dict_metadata)
        # only keys the checkpoint actually holds are restored: a target key
        # born after this checkpoint (e.g. an optimizer accumulator created
        # by a later step) keeps its current value instead of KeyError-ing
        # the whole resume
        array_target = {
            k: v for k, v in state_dict.items()
            if k in saved_arrays and k not in sidecar["state"]
        }
        if array_target:
            load_state_dict(array_target, path)
            state_dict.update(array_target)
        for k, v in sidecar["state"].items():
            state_dict[k] = v
        return {"step": int(sidecar["step"]), "extra": dict(sidecar["extra"])}
