"""Distributed checkpoint: sharded save + cross-mesh reshard-on-load,
crash-consistent (atomic writes, content-hashed manifests, managed
retention/validation via :class:`CheckpointManager`).

Reference: ``python/paddle/distributed/checkpoint/`` —
``save_state_dict.py:145``, ``load_state_dict.py:467``, ``metadata.py``.
"""

from paddle_tpu.distributed.checkpoint.load_state_dict import load_state_dict  # noqa: F401
from paddle_tpu.distributed.checkpoint.manager import (  # noqa: F401
    CheckpointManager,
    CheckpointRecord,
)
from paddle_tpu.distributed.checkpoint.metadata import (  # noqa: F401
    LocalTensorIndex,
    LocalTensorMetadata,
    Metadata,
    file_sha256,
)
from paddle_tpu.distributed.checkpoint.save_state_dict import save_state_dict  # noqa: F401
