"""``paddle_tpu.distributed`` (reference ``python/paddle/distributed``).

SPMD-first: a mesh + placements API backed by GSPMD, shard_map parallel
regions for explicit collectives, and fleet-style hybrid-parallel wrappers.
"""

from paddle_tpu.distributed import auto_parallel  # noqa: F401
from paddle_tpu.distributed import checkpoint  # noqa: F401
from paddle_tpu.distributed import fleet  # noqa: F401
from paddle_tpu.distributed import sharding  # noqa: F401
from paddle_tpu.distributed import utils  # noqa: F401
from paddle_tpu.distributed.api import (  # noqa: F401
    ShardDataloader,
    dtensor_from_local,
    dtensor_to_local,
    get_placements,
    reshard,
    shard_dataloader,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)
from paddle_tpu.distributed.collective import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    get_group,
    irecv,
    isend,
    new_group,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
)
from paddle_tpu.distributed.mesh import ProcessMesh, get_mesh, init_mesh, set_mesh  # noqa: F401
from paddle_tpu.distributed.parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from paddle_tpu.distributed.placements import Partial, Placement, Replicate, Shard  # noqa: F401
from paddle_tpu.distributed.resilient import resilient_train_loop  # noqa: F401
from paddle_tpu.distributed.store import Store, TCPStore  # noqa: F401
from paddle_tpu.distributed.watchdog import CommWatchdog, WatchdogTimeout  # noqa: F401
