"""Collective communication API.

Reference surface: ``python/paddle/distributed/communication/`` (all_reduce,
all_gather, …, ``group.py`` Group objects) over ProcessGroupNCCL. TPU-native
design (SURVEY §5.8): a single XLA-collective backend — inside ``shard_map``
parallel regions these lower to ``lax.psum``/``all_gather``/``ppermute`` over
ICI; on global-view (GSPMD) arrays, cross-device reduction/gather is expressed
by resharding, which XLA implements with the same collectives. There is no
NCCL: the compiler emits the communication.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability import devprof as _devprof
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.testing.faults import fault_point as _fault_point

_coll_calls = _obs.GLOBAL_METRICS.counter(
    "collective_calls_total",
    "Collective API invocations, by op.",
    labelnames=("op",),
)
_coll_seconds = _obs.GLOBAL_METRICS.counter(
    "collective_seconds_total",
    "Host-side wall time spent inside collective wrappers, by op (trace time "
    "under jit; eager dispatch time otherwise).",
    labelnames=("op",),
)


def _instrumented(fn):
    """Wrap one collective with call/time counters, a fault-injection site
    (``collective.<op>``) and a tracer span (trace time under jit; eager
    dispatch time otherwise). With metrics and tracing off and no fault
    plan installed the wrapper is three cached-bool checks — safe on
    trace-time hot paths."""
    op = fn.__name__
    fault_site = f"collective.{op}"
    span_name = f"collective.{op}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        _fault_point(fault_site)
        # full-rate tracing only: a collective carries no request context
        # to sample against, so at a partial rate these spans would flood
        # the bounded ring and evict the sampled request trees
        traced = _tracing.tracing_full()
        # devprof comm window: armed (thread-locally) only while a SAMPLED
        # engine step is in flight — its per-op timings become that step's
        # MEASURED collective share (comm_source: "wrapper")
        comm_win = _devprof.comm_window_armed()
        if not _obs.metrics_enabled() and not traced and not comm_win:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            t1 = time.perf_counter()
            if _obs.metrics_enabled():
                _coll_calls.labels(op=op).inc()
                _coll_seconds.labels(op=op).inc(t1 - t0)
            if traced:
                _tracing.GLOBAL_TRACER.add_span(span_name, start_s=t0, end_s=t1)
            if comm_win:
                _devprof.record_comm(op, t1 - t0)

    return wrapper

__all__ = [
    "ReduceOp",
    "Group",
    "new_group",
    "get_group",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "reduce",
    "reduce_scatter",
    "broadcast",
    "scatter",
    "alltoall",
    "alltoall_single",
    "send",
    "recv",
    "isend",
    "irecv",
    "ppermute",
    "P2POp",
    "batch_isend_irecv",
    "barrier",
    "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


@dataclass
class Group:
    """Communication group ≈ a named mesh axis (reference Group over
    ProcessGroup). ``axis_name`` binds collectives inside shard_map regions.

    ``axis_index_groups`` (optional) restricts the collective to rank
    SUBGROUPS of the axis — the XLA-native form of reference
    ``new_group(ranks=[...])`` sub-communicators: a partition of the axis into
    equally-sized index lists, forwarded to ``lax.psum``/``all_gather``/…
    (``ranks`` then holds this group's own axis indices)."""

    id: int
    ranks: List[int]
    axis_name: Optional[str] = None
    axis_index_groups: Optional[List[List[int]]] = None

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self) -> "Group":
        return self

    def _pos_in_group(self) -> np.ndarray:
        """axis index -> position within its subgroup (identity layout when
        the group spans the whole axis)."""
        if self.axis_index_groups is None:
            return np.arange(len(self.ranks))
        size = sum(len(g) for g in self.axis_index_groups)
        table = np.zeros(size, np.int32)
        for grp in self.axis_index_groups:
            for pos, idx in enumerate(grp):
                table[idx] = pos
        return table

    def _member_at(self, pos: int) -> np.ndarray:
        """axis index -> the axis index of its own subgroup's member ``pos``
        (whole-axis group: group-local position IS the axis index)."""
        if self.axis_index_groups is None:
            return np.full(len(self.ranks), pos, np.int32)
        size = sum(len(g) for g in self.axis_index_groups)
        table = np.zeros(size, np.int32)
        for grp in self.axis_index_groups:
            for idx in grp:
                table[idx] = grp[pos]
        return table


_groups: Dict[int, Group] = {}
_next_group_id = [0]


def _default_group() -> Group:
    if 0 not in _groups:
        n = len(jax.devices())
        _groups[0] = Group(0, list(range(n)), axis_name=None)
    return _groups[0]


def new_group(
    ranks: Optional[Sequence[int]] = None,
    backend: Optional[str] = None,
    timeout: Any = None,
    axis_name: Optional[str] = None,
    axis_size: Optional[int] = None,
) -> Group:
    """Create a communication group (reference ``paddle.distributed.new_group``).

    Two forms:
      - ``new_group(global_ranks, axis_name=...)`` — a mesh-axis-wide group
        (the fleet topology path; ``ranks`` are global device ids).
      - ``new_group(axis_indices, axis_name=..., axis_size=N)`` — a true
        SUB-group of an N-wide axis: collectives run only among those axis
        indices (``lax`` ``axis_index_groups``). The remaining indices are
        partitioned into sibling groups of the same size, so ``[0, 2]`` of a
        4-wide axis yields the partition ``[[0, 2], [1, 3]]``.
    """
    _next_group_id[0] += 1
    gid = _next_group_id[0]
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    ranks = list(ranks)
    aig = None
    if axis_size is not None and len(ranks) < axis_size:
        if any(r < 0 or r >= axis_size for r in ranks):
            raise ValueError(f"subgroup ranks {ranks} out of range for axis size {axis_size}")
        rest = [r for r in range(axis_size) if r not in ranks]
        k = len(ranks)
        if len(rest) % k != 0:
            raise ValueError(
                f"cannot partition the remaining {len(rest)} axis indices into "
                f"sibling groups of size {k} (XLA axis_index_groups must be a "
                f"partition into equal sizes)"
            )
        aig = [sorted(ranks)] + [rest[i : i + k] for i in range(0, len(rest), k)]
        ranks = sorted(ranks)
    g = Group(gid, ranks, axis_name=axis_name, axis_index_groups=aig)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _default_group()
    return _groups[gid]


def _in_parallel_trace() -> bool:
    """True when called inside a shard_map/pmap region with named axes."""
    try:
        from jax._src.core import get_axis_env  # jax>=0.5 internal; fallback below

        return bool(get_axis_env().axis_sizes)
    except Exception:  # jax-internal API; moved across versions — try the older one
        try:
            frame = jax.core.unsafe_get_axis_names()  # type: ignore[attr-defined]
            return bool(frame)
        except Exception:  # neither internal exists: treat as "not in a mapped trace"
            return False


def _axis(group: Optional[Group]) -> Optional[str]:
    g = group or _default_group()
    return g.axis_name


def _apply(t: Any, fn: Any) -> Any:
    if isinstance(t, Tensor):
        from paddle_tpu.core.dispatch import call_op

        return call_op("collective", fn, t)
    return fn(t)


@_instrumented
def all_reduce(tensor: Any, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    """AllReduce. Inside a shard_map region: ``lax.psum`` over the group axis
    (restricted to the group's ``axis_index_groups`` for sub-groups). On a
    global-view array (SPMD single-controller): values are already globally
    consistent — identity (the reduction lives in the sharding propagation),
    matching the DistTensor Partial→Replicate semantics."""
    axis = _axis(group)
    if axis is None:
        return tensor
    aig = (group or _default_group()).axis_index_groups

    def fn(x: Any) -> Any:
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, axis, axis_index_groups=aig)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, axis, axis_index_groups=aig)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, axis, axis_index_groups=aig)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, axis, axis_index_groups=aig)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(x), axis, axis_index_groups=aig))
        raise ValueError(f"unknown reduce op {op}")

    result = _apply(tensor, fn)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


@_instrumented
def all_gather(tensor_list: Optional[List[Any]], tensor: Any, group: Optional[Group] = None, sync_op: bool = True, axis: int = 0) -> Any:
    """AllGather. With ``tensor_list`` given: appends each member's tensor
    (reference list form). Without: returns the shards CONCATENATED along
    ``axis`` (reference functional form)."""
    axis_name = _axis(group)
    if axis_name is None:
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    aig = (group or _default_group()).axis_index_groups

    if tensor_list is not None:
        gathered = _apply(
            tensor,
            lambda x: jax.lax.all_gather(x, axis_name, axis_index_groups=aig, tiled=False),
        )
        from paddle_tpu.ops.manipulation import unbind

        tensor_list.extend(unbind(gathered, axis=0))
        return tensor_list
    return _apply(
        tensor,
        lambda x: jax.lax.all_gather(
            x, axis_name, axis_index_groups=aig, axis=axis, tiled=True
        ),
    )


# per-process call counter for all_gather_object: the collective contract
# (every process calls in the same order) makes matching counters a unique
# per-call key namespace in the shared coordination store
_ago_calls = [0]


@_instrumented
def all_gather_object(
    object_list: List[Any],
    obj: Any,
    group: Optional[Group] = None,
    timeout_s: float = 120.0,
) -> None:
    """Gather one picklable object from every PROCESS into ``object_list``
    (reference ``communication/all_gather.py:all_gather_object``), process-
    rank order. Single-process: appends ``obj`` (the in-process SPMD view —
    every "rank" already holds the global value).

    Multi-process: the exchange runs over the **jax.distributed coordination
    service** (the TCPStore analog ``init_parallel_env`` wired up), NOT an
    XLA computation — so it works on every backend, including CPU where
    cross-process XLA collectives are unavailable. Each process publishes
    its pickled payload under a per-call key and blocking-reads every peer's;
    the collective contract (all processes call in the same order) makes the
    per-process call counter a consistent key namespace. Only ``group=None``
    is supported here: a :class:`Group`'s ranks are DEVICE/axis ids, not
    process ids, and silently reading one namespace as the other would hang
    the gather — so it raises instead."""
    if jax.process_count() <= 1:
        object_list.append(obj)
        return
    if group is not None:
        raise NotImplementedError(
            "all_gather_object gathers one object per PROCESS; Group ranks "
            "are device/axis ids, so subgroup gathers are not supported in "
            "multi-process mode — call with group=None (all processes)"
        )
    import base64
    import pickle

    from jax._src import distributed as _jdist

    client = _jdist.global_state.client
    if client is None:  # pragma: no cover - initialize() always sets it
        raise RuntimeError(
            "all_gather_object needs jax.distributed initialized "
            "(init_parallel_env) in multi-process mode"
        )
    rank = jax.process_index()
    members = tuple(range(jax.process_count()))
    n = _ago_calls[0]
    _ago_calls[0] += 1
    prefix = f"paddle_tpu/all_gather_object/{n}"
    payload = base64.b64encode(pickle.dumps(obj)).decode("ascii")
    client.key_value_set(f"{prefix}/{rank}", payload)
    timeout_ms = max(int(timeout_s * 1000.0), 1)
    try:
        for r in members:
            raw = client.blocking_key_value_get(f"{prefix}/{r}", timeout_ms)
            object_list.append(pickle.loads(base64.b64decode(raw)))
        # every member has read every key past this barrier, so deleting our
        # payload below cannot strand a healthy peer's read
        client.wait_at_barrier(f"{prefix}/done", timeout_ms, list(members))
    finally:
        # success or not, this process's payload must not outlive the call —
        # a long-lived process gathering periodically (and a gather aborted
        # by a dead peer) must not grow the coordinator's store unboundedly;
        # on the failure path every member is timing out on the same missing
        # key, so the collective is already failing collectively
        client.key_value_delete(f"{prefix}/{rank}")


@_instrumented
def reduce(tensor: Any, dst: int = 0, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    """Reduce-to-one: only the ``dst`` member keeps the reduced value; every
    other member's tensor is unchanged (reference ``communication/reduce.py``
    semantics — NOT an all_reduce)."""
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    dst_local = g.get_group_rank(dst)
    if dst_local < 0:
        raise ValueError(f"dst rank {dst} is not a member of group {g.ranks}")
    dst_table = jnp.asarray(g._member_at(dst_local))
    aig = g.axis_index_groups

    def fn(x: Any) -> Any:
        if op == ReduceOp.SUM:
            red = jax.lax.psum(x, axis_name, axis_index_groups=aig)
        elif op == ReduceOp.MAX:
            red = jax.lax.pmax(x, axis_name, axis_index_groups=aig)
        elif op == ReduceOp.MIN:
            red = jax.lax.pmin(x, axis_name, axis_index_groups=aig)
        elif op == ReduceOp.AVG:
            red = jax.lax.pmean(x, axis_name, axis_index_groups=aig)
        elif op == ReduceOp.PROD:
            red = jnp.exp(jax.lax.psum(jnp.log(x), axis_name, axis_index_groups=aig))
        else:
            raise ValueError(f"unknown reduce op {op}")
        idx = jax.lax.axis_index(axis_name)
        return jnp.where(idx == dst_table[idx], red, x)

    result = _apply(tensor, fn)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


@_instrumented
def reduce_scatter(tensor: Any, tensor_list: Any = None, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor_list if tensor_list is not None else tensor
    aig = (group or _default_group()).axis_index_groups

    def fn(x: Any) -> Any:
        return jax.lax.psum_scatter(x, axis_name, axis_index_groups=aig, tiled=True)

    src = tensor_list if tensor_list is not None else tensor
    result = _apply(src, fn)
    # reference in-place semantics (communication/reduce_scatter.py): when an
    # output buffer is provided alongside the input list, write into it —
    # ported scripts read the buffer. Single-argument form: the tensor is the
    # INPUT; mutating it would clobber the caller's buffer with a
    # differently-shaped shard, so return the result instead.
    if tensor_list is not None and isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


@_instrumented
def broadcast(tensor: Any, src: int = 0, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    local_src = g.get_group_rank(src)
    if local_src < 0:
        raise ValueError(f"src rank {src} is not a member of group {g.ranks}")

    aig = g.axis_index_groups

    def fn(x: Any) -> Any:
        # select the src member's value on every member (gathered axis is
        # indexed by group-local position, not global rank)
        return jax.lax.all_gather(x, axis_name, axis_index_groups=aig)[local_src]

    result = _apply(tensor, fn)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


@_instrumented
def scatter(tensor: Any, tensor_list: Any = None, src: int = 0, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    local_src = g.get_group_rank(src)
    if local_src < 0:
        raise ValueError(f"src rank {src} is not a member of group {g.ranks}")

    aig = g.axis_index_groups
    pos_table = jnp.asarray(g._pos_in_group())

    def fn(x: Any) -> Any:
        idx = jax.lax.axis_index(axis_name)
        gathered = jax.lax.all_gather(x, axis_name, axis_index_groups=aig)
        return gathered[local_src][pos_table[idx]]

    result = _apply(tensor_list if tensor_list is not None else tensor, fn)
    # reference in-place semantics (communication/scatter.py): only when the
    # tensor is a dedicated OUTPUT buffer (input came via tensor_list) — in
    # the single-argument form the tensor is the input and must not be
    # clobbered with the differently-shaped shard.
    if tensor_list is not None and isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


@_instrumented
def alltoall(out_tensor_list: Any, in_tensor_list: Any, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(in_tensor_list)
        return out_tensor_list

    from paddle_tpu.ops.manipulation import stack, unbind

    stacked = stack(in_tensor_list, axis=0) if isinstance(in_tensor_list, list) else in_tensor_list
    aig = (group or _default_group()).axis_index_groups

    def fn(x: Any) -> Any:
        return jax.lax.all_to_all(
            x, axis_name, split_axis=0, concat_axis=0, axis_index_groups=aig, tiled=False
        )

    result = _apply(stacked, fn)
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(unbind(result, axis=0))
        return out_tensor_list
    return result


@_instrumented
def alltoall_single(
    out_tensor: Any,
    in_tensor: Any,
    in_split_sizes: Any = None,
    out_split_sizes: Any = None,
    group: Optional[Group] = None,
    sync_op: bool = True,
) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return in_tensor
    aig = (group or _default_group()).axis_index_groups

    def fn(x: Any) -> Any:
        return jax.lax.all_to_all(
            x, axis_name, split_axis=0, concat_axis=0, axis_index_groups=aig, tiled=True
        )

    return _apply(in_tensor, fn)


@_instrumented
def ppermute(tensor: Any, perm: Sequence[Any], group: Optional[Group] = None) -> Any:
    """Point-to-point permutation over the group axis: ``perm`` is a list of
    (src_group_rank, dst_group_rank) pairs (each destination at most once) —
    the XLA collective-permute that pipeline p2p compiles to. For a sub-group,
    the same group-local permutation is applied within EVERY sibling subgroup
    (SPMD programs are identical across ranks)."""
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    if g.axis_index_groups is not None:
        pairs = [
            (grp[a], grp[b]) for grp in g.axis_index_groups for a, b in perm
        ]
    else:
        pairs = [tuple(p) for p in perm]

    def fn(x: Any) -> Any:
        return jax.lax.ppermute(x, axis_name, pairs)

    return _apply(tensor, fn)


# internal p2p helpers call the UNWRAPPED ppermute: send/recv/batch_isend_irecv
# are themselves instrumented, and nesting would double-count every p2p edge
# under op="ppermute" (calls and overlapping wall time)
_ppermute_raw = ppermute.__wrapped__


@_instrumented
def send(tensor: Any, dst: int = 0, group: Optional[Group] = None, sync_op: bool = True, src: Optional[int] = None) -> Any:
    """Pairwise send. SPMD programs are rank-agnostic, so the source must be
    explicit: ``send(t, dst=k, src=j)`` ≡ ``ppermute(t, [(j, k)])``. Use
    :func:`ppermute` or :func:`batch_isend_irecv` for pipeline-style shifts
    (reference p2p: ``pp_utils/p2p_communication.py`` batched isend/irecv)."""
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    if src is None:
        raise ValueError(
            "SPMD p2p needs an explicit source: send(t, dst=k, src=j), or use "
            "dist.ppermute/batch_isend_irecv for shift patterns"
        )
    g = group or _default_group()
    return _ppermute_raw(tensor, [(g.get_group_rank(src), g.get_group_rank(dst))], group)


@_instrumented
def recv(tensor: Any, src: int = 0, group: Optional[Group] = None, sync_op: bool = True, dst: Optional[int] = None) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    if dst is None:
        raise ValueError(
            "SPMD p2p needs an explicit destination: recv(t, src=j, dst=k), or "
            "use dist.ppermute/batch_isend_irecv for shift patterns"
        )
    g = group or _default_group()
    result = _ppermute_raw(tensor, [(g.get_group_rank(src), g.get_group_rank(dst))], group)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


class P2POp:
    """One element of a batched p2p exchange (reference
    ``paddle.distributed.P2POp`` used by ``batch_isend_irecv``).

    SPMD programs are rank-agnostic, so both endpoints must be named:
      - ``P2POp(isend, t, peer, src=j)``: member ``j`` sends its ``t`` to
        ``peer``.
      - ``P2POp(irecv, buf, peer, src=k)``: member ``k`` receives from
        ``peer`` — i.e. the pair (peer → k); ``buf`` is the (shared-name)
        buffer whose per-member values carry the payload in the SPMD view.
    """

    def __init__(self, op: Any, tensor: Any, peer: int, group: Optional[Group] = None, src: Optional[int] = None) -> None:
        self.op = op  # dist.isend / dist.irecv
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.src = src


@_instrumented
def batch_isend_irecv(p2p_op_list: Sequence[P2POp]) -> List[Any]:
    """Batched p2p (reference ``pp_utils/p2p_communication.py:570``
    ``_p2p_helper`` batched isend/irecv): ALL ops touching the same buffer
    fold into ONE collective-permute (e.g. a bidirectional ring shift is a
    single ppermute with forward and backward pairs), and distinct buffers
    each get their own — XLA schedules them concurrently, the async-stream
    behavior the reference hand-codes. Returns one result per op, aligned
    with ``p2p_op_list``."""
    if not p2p_op_list:
        return []
    group = p2p_op_list[0].group
    g = group or _default_group()

    def pair_of(op: P2POp):
        if op.src is None:
            raise ValueError(
                "SPMD p2p needs both endpoints: P2POp(isend, t, peer, src=j) "
                "or P2POp(irecv, buf, peer, src=k)"
            )
        if op.op in (send, isend):
            a, b = op.src, op.peer  # src sends to peer
        elif op.op in (recv, irecv):
            a, b = op.peer, op.src  # src receives from peer
        else:
            raise ValueError(f"P2POp.op must be isend/irecv, got {op.op!r}")
        la, lb = g.get_group_rank(a), g.get_group_rank(b)
        if la < 0 or lb < 0:
            raise ValueError(f"p2p endpoints ({a}, {b}) not in group {g.ranks}")
        return (la, lb)

    # fold ops per distinct buffer; dedupe pairs (a send and its matching
    # recv describe the same edge)
    buffers: List[Any] = []
    buf_ids: List[int] = []
    pairs_per_buf: List[List[Any]] = []
    op_slots: List[Any] = []  # (buffer_index) per op
    for op in p2p_op_list:
        tid = id(op.tensor)
        if tid not in buf_ids:
            buf_ids.append(tid)
            buffers.append(op.tensor)
            pairs_per_buf.append([])
        bi = buf_ids.index(tid)
        pr = pair_of(op)
        if pr not in pairs_per_buf[bi]:
            pairs_per_buf[bi].append(pr)
        op_slots.append(bi)

    results = [
        _ppermute_raw(buf, pairs, group) for buf, pairs in zip(buffers, pairs_per_buf)
    ]
    return [results[bi] for bi in op_slots]


isend = send
irecv = recv


@_instrumented
def barrier(group: Optional[Group] = None) -> None:
    """Device-level barrier: flush async dispatch."""
    from paddle_tpu.core.device import device

    device.synchronize()


class stream:  # noqa: N801 - submodule-style namespace (communication.stream parity)
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    alltoall = staticmethod(alltoall)
    send = staticmethod(send)
    recv = staticmethod(recv)
