"""Collective communication API.

Reference surface: ``python/paddle/distributed/communication/`` (all_reduce,
all_gather, …, ``group.py`` Group objects) over ProcessGroupNCCL. TPU-native
design (SURVEY §5.8): a single XLA-collective backend — inside ``shard_map``
parallel regions these lower to ``lax.psum``/``all_gather``/``ppermute`` over
ICI; on global-view (GSPMD) arrays, cross-device reduction/gather is expressed
by resharding, which XLA implements with the same collectives. There is no
NCCL: the compiler emits the communication.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "ReduceOp",
    "Group",
    "new_group",
    "get_group",
    "all_reduce",
    "all_gather",
    "all_gather_object",
    "reduce",
    "reduce_scatter",
    "broadcast",
    "scatter",
    "alltoall",
    "alltoall_single",
    "send",
    "recv",
    "isend",
    "irecv",
    "ppermute",
    "P2POp",
    "batch_isend_irecv",
    "barrier",
    "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


@dataclass
class Group:
    """Communication group ≈ a named mesh axis (reference Group over
    ProcessGroup). ``axis_name`` binds collectives inside shard_map regions."""

    id: int
    ranks: List[int]
    axis_name: Optional[str] = None

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self) -> "Group":
        return self


_groups: Dict[int, Group] = {}
_next_group_id = [0]


def _default_group() -> Group:
    if 0 not in _groups:
        n = len(jax.devices())
        _groups[0] = Group(0, list(range(n)), axis_name=None)
    return _groups[0]


def new_group(ranks: Optional[Sequence[int]] = None, backend: Optional[str] = None, timeout: Any = None, axis_name: Optional[str] = None) -> Group:
    _next_group_id[0] += 1
    gid = _next_group_id[0]
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    g = Group(gid, list(ranks), axis_name=axis_name)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _default_group()
    return _groups[gid]


def _in_parallel_trace() -> bool:
    """True when called inside a shard_map/pmap region with named axes."""
    try:
        from jax._src.core import get_axis_env  # jax>=0.5 internal; fallback below

        return bool(get_axis_env().axis_sizes)
    except Exception:
        try:
            frame = jax.core.unsafe_get_axis_names()  # type: ignore[attr-defined]
            return bool(frame)
        except Exception:
            return False


def _axis(group: Optional[Group]) -> Optional[str]:
    g = group or _default_group()
    return g.axis_name


def _apply(t: Any, fn: Any) -> Any:
    if isinstance(t, Tensor):
        from paddle_tpu.core.dispatch import call_op

        return call_op("collective", fn, t)
    return fn(t)


def all_reduce(tensor: Any, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    """AllReduce. Inside a shard_map region: ``lax.psum`` over the group axis.
    On a global-view array (SPMD single-controller): values are already
    globally consistent — identity (the reduction lives in the sharding
    propagation), matching the DistTensor Partial→Replicate semantics."""
    axis = _axis(group)
    if axis is None:
        return tensor

    def fn(x: Any) -> Any:
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, axis)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, axis)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(x), axis))
        raise ValueError(f"unknown reduce op {op}")

    result = _apply(tensor, fn)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


def all_gather(tensor_list: Optional[List[Any]], tensor: Any, group: Optional[Group] = None, sync_op: bool = True, axis: int = 0) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor

    def fn(x: Any) -> Any:
        return jax.lax.all_gather(x, axis_name, tiled=False)

    gathered = _apply(tensor, fn)
    if tensor_list is not None:
        n = (group or _default_group()).nranks
        from paddle_tpu.ops.manipulation import unbind

        tensor_list.extend(unbind(gathered, axis=0))
        return tensor_list
    return gathered


def all_gather_object(object_list: List[Any], obj: Any, group: Optional[Group] = None) -> None:
    object_list.append(obj)


def reduce(tensor: Any, dst: int = 0, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    return all_reduce(tensor, op=op, group=group)


def reduce_scatter(tensor: Any, tensor_list: Any = None, op: str = ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor_list if tensor_list is not None else tensor

    def fn(x: Any) -> Any:
        return jax.lax.psum_scatter(x, axis_name, tiled=True)

    src = tensor_list if tensor_list is not None else tensor
    return _apply(src, fn)


def broadcast(tensor: Any, src: int = 0, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    local_src = g.get_group_rank(src)
    if local_src < 0:
        raise ValueError(f"src rank {src} is not a member of group {g.ranks}")

    def fn(x: Any) -> Any:
        # select the src member's value on every member (gathered axis is
        # indexed by group-local position, not global rank)
        return jax.lax.all_gather(x, axis_name)[local_src]

    result = _apply(tensor, fn)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


def scatter(tensor: Any, tensor_list: Any = None, src: int = 0, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    g = group or _default_group()
    local_src = g.get_group_rank(src)
    if local_src < 0:
        raise ValueError(f"src rank {src} is not a member of group {g.ranks}")

    def fn(x: Any) -> Any:
        idx = jax.lax.axis_index(axis_name)
        return jax.lax.all_gather(x, axis_name)[local_src][idx]

    return _apply(tensor_list if tensor_list is not None else tensor, fn)


def alltoall(out_tensor_list: Any, in_tensor_list: Any, group: Optional[Group] = None, sync_op: bool = True) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(in_tensor_list)
        return out_tensor_list

    from paddle_tpu.ops.manipulation import stack, unbind

    stacked = stack(in_tensor_list, axis=0) if isinstance(in_tensor_list, list) else in_tensor_list

    def fn(x: Any) -> Any:
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=False)

    result = _apply(stacked, fn)
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(unbind(result, axis=0))
        return out_tensor_list
    return result


def alltoall_single(
    out_tensor: Any,
    in_tensor: Any,
    in_split_sizes: Any = None,
    out_split_sizes: Any = None,
    group: Optional[Group] = None,
    sync_op: bool = True,
) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return in_tensor

    def fn(x: Any) -> Any:
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=True)

    return _apply(in_tensor, fn)


def ppermute(tensor: Any, perm: Sequence[Any], group: Optional[Group] = None) -> Any:
    """Point-to-point permutation over the group axis: ``perm`` is a list of
    (src_group_rank, dst_group_rank) pairs (each destination at most once) —
    the XLA collective-permute that pipeline p2p compiles to."""
    axis_name = _axis(group)
    if axis_name is None:
        return tensor

    def fn(x: Any) -> Any:
        return jax.lax.ppermute(x, axis_name, [tuple(p) for p in perm])

    return _apply(tensor, fn)


def send(tensor: Any, dst: int = 0, group: Optional[Group] = None, sync_op: bool = True, src: Optional[int] = None) -> Any:
    """Pairwise send. SPMD programs are rank-agnostic, so the source must be
    explicit: ``send(t, dst=k, src=j)`` ≡ ``ppermute(t, [(j, k)])``. Use
    :func:`ppermute` or :func:`batch_isend_irecv` for pipeline-style shifts
    (reference p2p: ``pp_utils/p2p_communication.py`` batched isend/irecv)."""
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    if src is None:
        raise ValueError(
            "SPMD p2p needs an explicit source: send(t, dst=k, src=j), or use "
            "dist.ppermute/batch_isend_irecv for shift patterns"
        )
    g = group or _default_group()
    return ppermute(tensor, [(g.get_group_rank(src), g.get_group_rank(dst))], group)


def recv(tensor: Any, src: int = 0, group: Optional[Group] = None, sync_op: bool = True, dst: Optional[int] = None) -> Any:
    axis_name = _axis(group)
    if axis_name is None:
        return tensor
    if dst is None:
        raise ValueError(
            "SPMD p2p needs an explicit destination: recv(t, src=j, dst=k), or "
            "use dist.ppermute/batch_isend_irecv for shift patterns"
        )
    g = group or _default_group()
    result = ppermute(tensor, [(g.get_group_rank(src), g.get_group_rank(dst))], group)
    if isinstance(tensor, Tensor) and isinstance(result, Tensor):
        tensor._replace_(result)
        return tensor
    return result


class P2POp:
    """One element of a batched p2p exchange (reference
    ``paddle.distributed.P2POp`` used by ``batch_isend_irecv``)."""

    def __init__(self, op: Any, tensor: Any, peer: int, group: Optional[Group] = None, src: Optional[int] = None) -> None:
        self.op = op  # dist.isend / dist.irecv
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.src = src


def batch_isend_irecv(p2p_op_list: Sequence[P2POp]) -> List[Any]:
    """Fuse a list of sends/recvs into one collective-permute. Send ops
    contribute (self→peer) pairs; each pair's source is the op's ``src``
    (defaulting to the matching recv's peer)."""
    if not p2p_op_list:
        return []
    group = p2p_op_list[0].group
    g = group or _default_group()
    perm = []
    tensor = None
    for op in p2p_op_list:
        if op.op is send or op.op is isend:
            src_rank = op.src if op.src is not None else 0
            perm.append((g.get_group_rank(src_rank), g.get_group_rank(op.peer)))
            tensor = op.tensor
    if tensor is None:
        tensor = p2p_op_list[0].tensor
    result = ppermute(tensor, perm, group)
    return [result]


isend = send
irecv = recv


def barrier(group: Optional[Group] = None) -> None:
    """Device-level barrier: flush async dispatch."""
    from paddle_tpu.core.device import device

    device.synchronize()


class stream:  # noqa: N801 - submodule-style namespace (communication.stream parity)
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    alltoall = staticmethod(alltoall)
    send = staticmethod(send)
    recv = staticmethod(recv)
