"""TCPStore: the rendezvous key-value store — re-export.

Reference: ``paddle/phi/core/distributed/store/tcp_store.h:121``. The
implementation lives in the stdlib-only package ``paddle_tpu_native.store``
so a bootstrap process can rendezvous even when the accelerator runtime is
unhealthy (importing ``paddle_tpu`` pulls in jax; the store must not).
"""

from paddle_tpu_native.store import Store, TCPStore  # noqa: F401

__all__ = ["TCPStore", "Store"]
