"""Tensor-parallel serving seam: the ``tp`` mesh the continuous-batching
engine shards itself over.

Reference: the fork's layer-7 distributed stack (``fleet``, ``auto_parallel``,
``ProcessGroup``) — here shaped for single-controller SPMD serving. One
engine = one shard group over a single-axis ``['tp']`` mesh:

- **Attention heads and the KV block pool partition per device.** The paged
  caches keep their ``[num_blocks, kv_heads, block_size, head_dim]`` layout
  and shard the HEAD dim, so a logical block id indexes the same slot in
  every shard's pool partition — the host-side allocator, block tables,
  prefix-cache chain hashes and refcounts stay replicated-by-construction
  (one copy on the host steering all shards), and head-parallel attention
  needs no communication inside the paged block walk.
- **MLP and projections split Megatron-style** (column-parallel
  qkv/gate/up, row-parallel o/down): GSPMD inserts exactly one all-reduce
  per layer at the row-parallel matmul.
- **The lm-head shards over vocab**; the greedy path's ``argmax`` over the
  vocab-sharded logits lowers to a sharded argmax + global max-combine
  (exact index tiebreak), preserving byte-identical outputs.

The engine stays ONE compiled signature under the mesh: sharding is carried
by the INPUT placements (committed params/caches), never by the program's
shapes, so the recompile watchdog still reports exactly one compile.

``tp_shard_context`` is a trace-time seam: the engine arms it around its
jitted dispatch so the paged-attention functional (which has no mesh
argument) can wrap the Pallas kernel in ``shard_map`` over the head shard —
a ``pallas_call`` has no SPMD partitioning rule, so without the wrapper
GSPMD would replicate the kernel; the XLA fallback path partitions under
plain GSPMD and needs no context.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "COLUMN_PARALLEL_LEAVES",
    "ROW_PARALLEL_LEAVES",
    "TP_AXIS",
    "VOCAB_PARALLEL_EMBEDDINGS",
    "analytic_cost_hints",
    "build_tp_mesh",
    "current_tp_mesh",
    "kv_cache_sharding",
    "replicated",
    "row_parallel_overlap_matmul",
    "shard_model_params",
    "tp_param_spec",
    "tp_shard_context",
    "validate_tp",
]

TP_AXIS = "tp"

# THE Megatron leaf-name classification — the one placement table both the
# serving policy below and the training policy (models/llama.llama_shard_fn,
# mp axis) consume, so a new projection name (a fused qkv, an MoE expert
# linear) added here shards under both.
# Column-parallel leaves: weight [in, out] shards the OUT dim (their packed
# outputs are the per-head / per-neuron slices the next layer consumes
# shard-local); row-parallel leaves shard the IN dim — the one all-reduce
# per layer lands after their matmul. lm_head [hidden, vocab] shards vocab.
COLUMN_PARALLEL_LEAVES = (
    "q_proj", "k_proj", "v_proj", "gate_proj", "up_proj", "lm_head",
)
ROW_PARALLEL_LEAVES = ("o_proj", "down_proj")
# vocab-parallel embedding: weight [vocab, hidden] shards dim 0 — also the
# tied-embedding lm-head layout (matmul(x, W^T) contracts hidden, vocab
# stays sharded into the argmax)
VOCAB_PARALLEL_EMBEDDINGS = ("embed_tokens", "word_embeddings", "wte")


def build_tp_mesh(tp: int) -> Mesh:
    """Single-axis ``['tp']`` mesh over the first ``tp`` visible devices (on
    TPU, jax's default device order follows the physical ICI torus)."""
    devices = jax.devices()
    if tp > len(devices):
        raise ValueError(
            f"tp={tp} exceeds the {len(devices)} visible devices"
        )
    import numpy as np

    return Mesh(np.asarray(devices[:tp], dtype=object), (TP_AXIS,))


def validate_tp(tp: int, num_heads: int, num_kv_heads: int) -> None:
    """The head-parallel contract: ``tp`` must divide the KV heads (each
    shard owns whole KV heads of the pool partition) and the query heads
    (GQA groups follow their KV head onto the same shard)."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if num_kv_heads % tp != 0:
        raise ValueError(
            f"tp={tp} does not divide num_key_value_heads={num_kv_heads}: "
            "head-parallel attention shards whole KV heads"
        )
    if num_heads % tp != 0:
        raise ValueError(
            f"tp={tp} does not divide num_attention_heads={num_heads}"
        )


def tp_param_spec(name: str, ndim: int) -> PartitionSpec:
    """Megatron placement for one named parameter on the ``['tp']`` mesh,
    by leaf-name convention (``...self_attn.q_proj.weight``). A model may
    override per-name decisions by defining ``tp_param_spec(name, ndim)``
    (see :func:`shard_model_params`). Unknown leaves replicate — always
    correct, GSPMD just keeps them whole on every shard."""
    parts = name.split(".")
    leaf = parts[-1]
    owner = parts[-2] if len(parts) >= 2 else ""
    if leaf == "weight" and ndim == 2:
        if owner in COLUMN_PARALLEL_LEAVES:
            return PartitionSpec(None, TP_AXIS)
        if owner in ROW_PARALLEL_LEAVES:
            return PartitionSpec(TP_AXIS, None)
        if owner in VOCAB_PARALLEL_EMBEDDINGS:
            return PartitionSpec(TP_AXIS, None)
    return PartitionSpec(*([None] * ndim))


def replicated(mesh: Mesh, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*([None] * ndim)))


def kv_cache_sharding(mesh: Mesh) -> NamedSharding:
    """The pool partition: ``[num_blocks, kv_heads, block_size, head_dim]``
    sharded on the HEAD dim — every shard holds the same logical blocks
    (same ids, same offsets) for its own slice of the heads."""
    return NamedSharding(mesh, PartitionSpec(None, TP_AXIS, None, None))


def shard_model_params(model: Any, mesh: Mesh) -> int:
    """Commit every named parameter onto the mesh per the Megatron policy
    (model-provided ``tp_param_spec(name, ndim)`` wins when defined);
    returns how many params got a genuinely split placement. In-place:
    serving owns the model — the engine is the unit of deployment."""
    policy = getattr(model, "tp_param_spec", None) or tp_param_spec
    n_split = 0
    for name, p in model.named_parameters():
        spec = policy(name, p._data.ndim)
        if any(ax is not None for ax in spec):
            n_split += 1
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    return n_split


# -- trace-time context ------------------------------------------------------
# threading.local, not a contextvar: the serving pump drives each engine from
# its own thread, and the armed mesh must be visible exactly to the trace
# running on that thread.
class _TpState(threading.local):
    mesh: Optional[Mesh] = None


_STATE = _TpState()


def current_tp_mesh() -> Optional[Mesh]:
    """The mesh armed by the innermost :func:`tp_shard_context` on this
    thread (None = single-chip semantics). Read at TRACE time by the paged-
    attention functional to decide the shard_map wrapping."""
    return _STATE.mesh


def row_parallel_overlap_matmul(x: Any, weight: Any, tiles: int = 2) -> Any:
    """A row-parallel matmul (o_proj/down_proj: weight shards the IN dim)
    split into ``tiles`` independent token-row tiles — the "Tile-Level
    Activation Overlap" schedule. Under GSPMD each tile's partial matmul ends
    in its OWN all-reduce, so while tile t's collective is on the ICI wire,
    tile t+1's matmul (and the consumer of tile t-1's already-reduced rows)
    runs on the MXU — the per-layer all-reduce stops serializing against the
    whole layer. Per-row contraction is untouched by the split, so the
    result is byte-identical to the plain matmul (tile boundaries only
    partition the BATCH rows; each output row's reduction order is
    unchanged).

    ``x`` is ``[..., rows, in]`` with the leading dims flattened into rows;
    falls back to one tile when the row count doesn't split evenly (serving
    batches are padded to the slot count, which divides)."""
    import jax.numpy as jnp

    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    x2 = x.reshape(rows, x.shape[-1])
    tiles = int(tiles)
    if tiles <= 1 or rows % tiles != 0:
        out = jnp.matmul(x2, weight)
        return out.reshape(*lead, weight.shape[-1])
    step = rows // tiles
    parts = [
        jnp.matmul(x2[t * step : (t + 1) * step], weight) for t in range(tiles)
    ]
    return jnp.concatenate(parts, axis=0).reshape(*lead, weight.shape[-1])


@contextlib.contextmanager
def tp_shard_context(mesh: Optional[Mesh]) -> Iterator[None]:
    """Arm ``mesh`` as the tensor-parallel shard group for traces started
    under this context (re-entrant; restores the previous value)."""
    prev = _STATE.mesh
    _STATE.mesh = mesh
    try:
        yield
    finally:
        _STATE.mesh = prev


def analytic_cost_hints(
    num_layers: int,
    hidden: int,
    intermediate: int,
    vocab: int,
    tokens: int,
    kv_len: int,
    tp: int = 1,
    dtype_bytes: int = 2,
    ici_bytes_per_s: float = 45e9,
    peak_flops_per_s: float = 197e12,
) -> dict:
    """Analytic per-category weights seeding devprof's attribution prior
    for one decode/prefill step over ``tokens`` query rows against a
    ``kv_len`` context. All weights are FLOP-denominated so the XLA cost
    model can reconcile against them: matmul and attention are literal flop
    counts (Megatron accounting — qkv+o 4h² and the gated MLP 3h·i per
    layer, plus the lm-head 2hV; attention 2·2·h·kv per layer); the
    collective weight converts the per-layer all-reduce's wire time
    (2 ramp-up·bytes/bw for a ring over ``tp`` shards) into
    flop-equivalents at peak so the three shares stay in one unit. These
    are the same ICI/MXU constants ``bench.py``'s analytic estimate uses —
    the point is that devprof's MEASURED shares can now be laid against
    this prior to validate it."""
    matmul = float(tokens) * (
        num_layers * 2.0 * (4.0 * hidden * hidden + 3.0 * hidden * intermediate)
        + 2.0 * hidden * vocab
    )
    attention = float(tokens) * num_layers * 2.0 * 2.0 * hidden * float(kv_len)
    collective = 0.0
    if tp > 1:
        # one row-parallel all-reduce per layer (o_proj + down_proj fold
        # into the same ring pass in the overlap path): ring all-reduce
        # moves 2*(tp-1)/tp of the activation per hop
        ar_bytes = (
            num_layers * float(tokens) * hidden * dtype_bytes
            * 2.0 * (tp - 1) / tp * 2.0  # two row-parallel matmuls per layer
        )
        collective = (ar_bytes / ici_bytes_per_s) * peak_flops_per_s
    return {"attention": attention, "matmul": matmul, "collective": collective}
