"""ZeRO stages as placements: 'os' (stage 1), 'os_g' (stage 2),
'p_g_os' (stage 3 — parameters themselves sharded).

Reference: ``python/paddle/distributed/sharding/group_sharded.py:50`` and the
stage implementations ``fleet/meta_parallel/sharding/group_sharded_stage2.py``
/ ``group_sharded_optimizer_stage2.py`` / ``group_sharded_stage3.py:85``.

TPU-native design: stage 3's "shard params, all-gather on use, free after
use" is exactly what GSPMD does when a parameter carries a ``Shard``
placement while the computation needs it replicated — XLA all-gathers it
right before use and the gathered buffer is temporary by construction. So
stage 3 here = permanently reshard the model's parameters over the sharding
axis; stages 1/2 = the sharded optimizer from
``dygraph_sharding_optimizer.py``. No wrapper classes intercepting forward
are needed, and the model's code is unchanged.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer.dygraph_sharding_optimizer import (
    DygraphShardingOptimizer,
    DygraphShardingOptimizerV2,
    _find_sharding_axis,
    sharded_placements,
)
from paddle_tpu.distributed.mesh import ProcessMesh, get_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(
    model: Any,
    optimizer: Any,
    level: str,
    scaler: Any = None,
    group: Any = None,
    offload: bool = False,
    sync_buffers: bool = False,
    buffer_max_size: int = 2**23,
    segment_size: int = 2**20,
    sync_comm: bool = False,
    dp_group: Any = None,
    exclude_layer: Any = None,
    mesh: Optional[ProcessMesh] = None,
    axis: Optional[str] = None,
) -> Tuple[Any, Any, Any]:
    """Apply ZeRO sharding at the given level; returns (model, optimizer,
    scaler) like the reference. ``offload`` (CPU state offload) is not
    implemented on TPU — HBM savings come from the sharding itself."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be one of 'os'/'os_g'/'p_g_os', got {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True is not supported: ZeRO placements already keep only "
            "1/N of states per device"
        )
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("group_sharded_parallel needs a mesh (dist.init_mesh/set_mesh)")
    axis = axis or _find_sharding_axis(mesh)
    if axis is None:
        raise ValueError(f"mesh {mesh} has no sharding-capable axis")

    if level == "p_g_os":
        # stage 3: persistently shard the parameters themselves
        import paddle_tpu
        from paddle_tpu.distributed.api import shard_tensor

        with paddle_tpu.no_grad():
            for p in model.parameters():
                plc = sharded_placements(p, mesh, axis)
                if plc is None:
                    continue
                d = shard_tensor(p, mesh, plc)
                p._data = d._data
                p.process_mesh = mesh
                p.placements = plc

    opt_cls = DygraphShardingOptimizerV2 if level in ("os_g", "p_g_os") else DygraphShardingOptimizer
    optimizer = opt_cls(optimizer, mesh=mesh, axis=axis)
    return model, optimizer, scaler


def save_group_sharded_model(model: Any, output: str, optimizer: Any = None) -> None:
    """Gather-and-save (reference ``group_sharded.py`` save path): global-view
    arrays already hold full values, so this is a plain save."""
    import paddle_tpu

    os.makedirs(output, exist_ok=True)
    paddle_tpu.save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        paddle_tpu.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
