"""Group-sharded (ZeRO) user API.

Reference: ``python/paddle/distributed/sharding/group_sharded.py``
(``group_sharded_parallel:50`` — levels 'os' / 'os_g' / 'p_g_os' mapping to
GroupShardedStage{1,2,3}; ``save_group_sharded_model``).
"""

from paddle_tpu.distributed.sharding.group_sharded import (  # noqa: F401
    group_sharded_parallel,
    save_group_sharded_model,
)
