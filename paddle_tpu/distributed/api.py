"""Auto-parallel DistTensor API: shard_tensor / reshard / shard_layer /
shard_optimizer.

Reference: ``python/paddle/distributed/auto_parallel/api.py``
(``shard_tensor:179``, ``reshard:675``, ``shard_layer:776``,
``shard_optimizer:1448``). TPU-native: a "DistTensor" IS a global jax.Array
with a NamedSharding — the (mesh, placements) pair maps 1:1 onto
(jax Mesh, PartitionSpec), and resharding is ``jax.device_put`` (XLA emits the
collective: all-gather for s→r, slice for r→s, all-to-all for s→s', psum for
p→r — the same pairwise functions the reference registers in
``paddle/phi/core/distributed/auto_parallel/reshard/``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.distributed.mesh import ProcessMesh, get_mesh
from paddle_tpu.distributed.placements import (
    Partial,
    Placement,
    Replicate,
    Shard,
    placements_to_spec,
    spec_to_placements,
)

__all__ = [
    "shard_tensor",
    "dtensor_from_local",
    "dtensor_to_local",
    "reshard",
    "shard_layer",
    "shard_optimizer",
    "unshard_dtensor",
    "get_placements",
    "apply_placement",
    "build_placements",
]


def apply_placement(param: Any, mesh: "ProcessMesh", placements: Sequence[Placement]) -> None:
    """Reshard a Parameter/buffer in place, outside the grad tape — the one
    idiom every shard_fn (llama/gpt/mpu/Experts) uses."""
    import paddle_tpu

    if param is None:
        return
    with paddle_tpu.no_grad():
        d = shard_tensor(param, mesh, placements)
    param._data = d._data
    param.process_mesh = mesh
    param.placements = list(placements)


def build_placements(mesh: "ProcessMesh", **axis_dims: int) -> List[Placement]:
    """``build_placements(mesh, mp=1, sharding=0)`` → Shard(dim) on each named
    axis present in the mesh, Replicate() elsewhere."""
    out: List[Placement] = [Replicate() for _ in mesh.dim_names]
    for axis_name, dim in axis_dims.items():
        if axis_name in mesh.dim_names and dim is not None:
            out[mesh.dim_names.index(axis_name)] = Shard(dim)
    return out


def _named_sharding(mesh: ProcessMesh, placements: Sequence[Placement], ndim: int) -> NamedSharding:
    spec = placements_to_spec(placements, ndim, mesh.dim_names)
    return NamedSharding(mesh.jax_mesh(), spec)


def shard_tensor(
    data: Any,
    mesh: Optional[ProcessMesh] = None,
    placements: Optional[Sequence[Placement]] = None,
    dtype: Any = None,
    place: Any = None,
    stop_gradient: Optional[bool] = None,
) -> Tensor:
    """Place a (global-view) tensor onto a mesh with placements."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("no mesh: pass mesh= or call dist.init_mesh/set_mesh first")
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = list(placements or [Replicate() for _ in range(mesh.ndim)])
    sharding = _named_sharding(mesh, placements, t.ndim)
    if isinstance(t._data, jax.core.Tracer):
        # Inside a jit trace: a placement is a GSPMD sharding constraint
        # (the analog of the reference's dist_op annotations on PIR values).
        arr = jax.lax.with_sharding_constraint(t._data, sharding)
    else:
        arr = jax.device_put(t._data, sharding)
    out_cls = Parameter if isinstance(t, Parameter) else Tensor
    out = out_cls(arr)
    out.stop_gradient = t.stop_gradient if stop_gradient is None else stop_gradient
    out.name = t.name
    out.process_mesh = mesh
    out.placements = placements
    return out


def dtensor_from_local(
    local_tensor: Tensor,
    mesh: ProcessMesh,
    placements: Sequence[Placement],
) -> Tensor:
    """Assemble a global DistTensor from per-shard local data
    (reference ``api.py:589``). Single-process SPMD: the local tensor is this
    process's shard batch; use make_array_from_single_device_arrays."""
    sharding = _named_sharding(mesh, placements, local_tensor.ndim)
    global_shape = list(local_tensor.shape)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            global_shape[p.dim % local_tensor.ndim] *= mesh.shape[mesh_dim]
    arr = jax.make_array_from_process_local_data(sharding, local_tensor.numpy(), tuple(global_shape))
    out = Tensor(arr, stop_gradient=local_tensor.stop_gradient)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def dtensor_to_local(dist_tensor: Tensor, mesh: Any = None, placements: Any = None) -> Tensor:
    """This process's addressable shard as a dense tensor."""
    arr = dist_tensor._data
    shards = [s.data for s in arr.addressable_shards]
    return Tensor(shards[0] if len(shards) == 1 else jnp.asarray(shards[0]))


def reshard(
    dist_tensor: Tensor,
    mesh: Optional[ProcessMesh] = None,
    placements: Optional[Sequence[Placement]] = None,
) -> Tensor:
    """Convert placements (reference ``api.py:675`` + reshard function
    registry). XLA chooses the collective from src/dst shardings."""
    mesh = mesh or getattr(dist_tensor, "process_mesh", None) or get_mesh()
    placements = list(placements or [])
    has_partial = any(isinstance(p, Partial) for p in placements)
    if has_partial:
        raise NotImplementedError(
            "reshard to Partial is not supported: GSPMD materializes partial "
            "values only inside compiled programs"
        )
    sharding = _named_sharding(mesh, placements, dist_tensor.ndim)
    if isinstance(dist_tensor._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(dist_tensor._data, sharding)
    else:
        arr = jax.device_put(dist_tensor._data, sharding)
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient)
    out.process_mesh = mesh
    out.placements = placements
    return out


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    mesh = getattr(dist_tensor, "process_mesh", None) or get_mesh()
    return reshard(dist_tensor, mesh, [Replicate() for _ in range(mesh.ndim)])


def get_placements(t: Tensor) -> Optional[List[Placement]]:
    if hasattr(t, "placements"):
        return t.placements
    arr = t._data
    sharding = getattr(arr, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return spec_to_placements(sharding.spec, sharding.mesh.axis_names)
    return None


def shard_layer(
    layer: Any,
    process_mesh: ProcessMesh,
    shard_fn: Optional[Callable] = None,
    input_fn: Optional[Callable] = None,
    output_fn: Optional[Callable] = None,
) -> Any:
    """Shard a Layer's parameters over a mesh (reference ``api.py:776``).

    ``shard_fn(name, layer, mesh)`` assigns placements per sublayer; default
    replicates every parameter.
    """
    import paddle_tpu

    def default_shard(name: str, sublayer: Any, mesh: ProcessMesh) -> None:
        for pname, p in sublayer._parameters.items():
            if p is None:
                continue
            d = shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])
            p._data = d._data
            p.process_mesh = mesh
            p.placements = d.placements

    fn = shard_fn or default_shard
    with paddle_tpu.no_grad():
        for name, sublayer in layer.named_sublayers(include_self=True):
            fn(name, sublayer, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def shard_optimizer(optimizer: Any, shard_fn: Optional[Callable] = None) -> Any:
    """ZeRO-style sharded optimizer states (reference ``api.py:1448``): state
    shards follow parameter placements; with a ``shard_fn`` (e.g. ShardOptimizer
    stage policies) accumulator arrays get their own shardings lazily at
    creation. The fused step runs under jit, so GSPMD partitions the update."""
    orig_state_for = optimizer._state_for

    def sharded_state_for(p: Tensor) -> Dict[str, Any]:
        st = orig_state_for(p)
        sharding = getattr(p._data, "sharding", None)
        if sharding is not None:
            for k, v in st.items():
                if hasattr(v, "shape") and tuple(v.shape) == tuple(p._data.shape):
                    st[k] = jax.device_put(v, sharding)
        return st

    optimizer._state_for = sharded_state_for
    return optimizer


class ShardDataloader:
    """Reference ``auto_parallel/api.py:shard_dataloader``: wrap a DataLoader
    so every yielded batch comes out as DistTensors sharded over the mesh.

    TPU-native semantics: the wrapped loader produces GLOBAL batches (this is
    a single-controller SPMD program — there is no per-rank loader process),
    and each tensor is placed with ``Shard(0)`` along ``shard_dims`` (the dp
    axis) and ``Replicate`` elsewhere; XLA partitions the actual transfer.
    ``is_dataset_splitted`` is accepted for API parity and must be False:
    a pre-split per-rank dataset implies the multi-controller model.
    """

    def __init__(self, dataloader: Any, meshes: Any, input_keys: Optional[Sequence[str]] = None,
                 shard_dims: Any = None, is_dataset_splitted: bool = False) -> None:
        if is_dataset_splitted:
            raise NotImplementedError(
                "single-controller SPMD feeds global batches; pre-split "
                "datasets (is_dataset_splitted=True) have no analog here"
            )
        if isinstance(meshes, (list, tuple)):
            if len(meshes) != 1:
                raise NotImplementedError(
                    "per-input mesh lists (pipeline-style placement) are not "
                    "supported; pass ONE mesh — under GSPMD the program, not "
                    "the loader, decides which stage consumes which input"
                )
            meshes = meshes[0]
        if input_keys is not None:
            raise NotImplementedError(
                "input_keys maps dict keys to per-input meshes; with a single "
                "mesh every key gets the same placement — omit input_keys"
            )
        if isinstance(shard_dims, (list, tuple)):
            if len(shard_dims) != 1:
                raise NotImplementedError(
                    "one shard_dim per (single) mesh; got a list of "
                    f"{len(shard_dims)}"
                )
            shard_dims = shard_dims[0]
        self._loader = dataloader
        self._mesh = meshes
        if shard_dims is None:
            self._placements = [Replicate() for _ in range(meshes.ndim)]
        else:
            axis = (
                meshes.dim_names.index(shard_dims)
                if isinstance(shard_dims, str) else int(shard_dims)
            )
            self._placements = [
                Shard(0) if i == axis else Replicate() for i in range(meshes.ndim)
            ]

    def _place(self, item: Any) -> Any:
        if isinstance(item, dict):
            return {k: self._place(v) for k, v in item.items()}
        if isinstance(item, (list, tuple)):
            parts = [self._place(v) for v in item]
            if hasattr(item, "_fields"):  # namedtuple batches
                return type(item)(*parts)
            return type(item)(parts)
        return shard_tensor(item, self._mesh, self._placements)

    def __iter__(self):
        for batch in self._loader:
            yield self._place(batch)

    def __len__(self) -> int:
        return len(self._loader)


def shard_dataloader(dataloader: Any, meshes: Any, input_keys: Optional[Sequence[str]] = None,
                     shard_dims: Any = None, is_dataset_splitted: bool = False) -> ShardDataloader:
    """Reference ``shard_dataloader`` parity — see :class:`ShardDataloader`."""
    return ShardDataloader(dataloader, meshes, input_keys, shard_dims, is_dataset_splitted)


__all__ += ["ShardDataloader", "shard_dataloader"]
