"""Placements: Shard / Replicate / Partial.

Reference: ``paddle/phi/core/distributed/auto_parallel/placement_types.h`` and
``python/paddle/distributed`` placement API. Mapped onto
``jax.sharding.PartitionSpec`` axes for GSPMD propagation.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec


class Placement:
    def is_shard(self, dim: Optional[int] = None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Shard(Placement):
    def __init__(self, dim: int) -> None:
        self.dim = int(dim)

    def get_dim(self) -> int:
        return self.dim

    def is_shard(self, dim: Optional[int] = None) -> bool:
        return dim is None or dim == self.dim

    def __repr__(self) -> str:
        return f"Shard(dim={self.dim})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self) -> int:
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __repr__(self) -> str:
        return "Replicate()"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Replicate)

    def __hash__(self) -> int:
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement. GSPMD materializes partial values only
    transiently; a Partial DistTensor is represented as an unreduced value and
    ``reshard`` inserts the psum (reference ``p_to_r_reshard_function.cc``)."""

    def __init__(self, reduce_type: str = "sum") -> None:
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __repr__(self) -> str:
        return f"Partial({self.reduce_type})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self) -> int:
        return hash(("partial", self.reduce_type))


def placements_to_spec(placements: Sequence[Placement], ndim: int, mesh_dim_names: Sequence[str]) -> PartitionSpec:
    """Convert per-mesh-dim placements to a PartitionSpec over tensor dims."""
    entries: List[Any] = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            axis_name = mesh_dim_names[mesh_dim]
            d = p.dim % ndim
            if entries[d] is None:
                entries[d] = axis_name
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (axis_name,)
            else:
                entries[d] = (entries[d], axis_name)
    return PartitionSpec(*entries)


def spec_to_placements(spec: PartitionSpec, mesh_dim_names: Sequence[str]) -> List[Placement]:
    placements: List[Placement] = [Replicate() for _ in mesh_dim_names]
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            placements[list(mesh_dim_names).index(name)] = Shard(tensor_dim)
    return placements
