"""ProcessMesh over jax.sharding.Mesh.

Reference: ``python/paddle/distributed/auto_parallel/process_mesh.py`` +
``phi::distributed::ProcessMesh`` (``process_mesh.h``). On TPU the mesh maps
onto the physical ICI torus via jax's device assignment; DCN (multi-slice)
axes go first (``jax.make_mesh`` handles allocation order).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh


class ProcessMesh:
    def __init__(
        self,
        mesh: Union[Sequence[Any], np.ndarray, None] = None,
        dim_names: Optional[Sequence[str]] = None,
        shape: Optional[Sequence[int]] = None,
        process_ids: Optional[Sequence[int]] = None,
    ) -> None:
        if mesh is not None:
            arr = np.asarray(mesh)
            self._shape = list(arr.shape)
            self._process_ids = arr.reshape(-1).tolist()
        else:
            self._shape = list(shape or [])
            self._process_ids = list(process_ids or range(int(np.prod(self._shape))))
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(len(self._shape))]
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return list(self._process_ids)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape)) if self._shape else 1

    def get_dim_size(self, name: str) -> int:
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, name: str) -> "ProcessMesh":
        axis = self._dim_names.index(name)
        perm = [axis] + [i for i in range(self.ndim) if i != axis]
        arr = np.asarray(self._process_ids).reshape(self._shape).transpose(perm)
        names = [self._dim_names[i] for i in perm]
        return ProcessMesh(arr, names)

    def jax_mesh(self) -> Mesh:
        """Materialize the jax Mesh over real devices (cached)."""
        if self._jax_mesh is None:
            devices = jax.devices()
            dev_map = {d.id: d for d in devices}
            try:
                chosen = np.asarray(
                    [dev_map[i] for i in self._process_ids], dtype=object
                ).reshape(self._shape)
            except KeyError:
                # process ids are logical ranks; fall back to positional devices
                chosen = np.asarray(devices[: self.size], dtype=object).reshape(self._shape)
            self._jax_mesh = Mesh(chosen, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ProcessMesh)
            and other._shape == self._shape
            and other._process_ids == self._process_ids
            and other._dim_names == self._dim_names
        )

    def __hash__(self) -> int:
        return hash((tuple(self._shape), tuple(self._process_ids), tuple(self._dim_names)))

    def __repr__(self) -> str:
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def init_mesh(dim_names: Sequence[str], shape: Optional[Sequence[int]] = None) -> ProcessMesh:
    """Build a mesh over all visible devices (``jax.make_mesh`` analog)."""
    n = len(jax.devices())
    if shape is None:
        shape = [n]
    mesh = ProcessMesh(shape=list(shape), dim_names=list(dim_names), process_ids=list(range(int(np.prod(shape)))))
    set_mesh(mesh)
    return mesh
