"""Communication/step watchdog: hang detection for compiled collective steps.

Reference: ``paddle/phi/core/distributed/comm_task_manager.h:37``
(``CommTaskManager`` + ``NCCLCommTask``): a background thread that watches
enqueued collectives, detects async errors and hangs, dumps diagnostics on
timeout and aborts the process so the job scheduler can relaunch.

TPU translation: XLA compiles collectives into the step program, so the unit
being watched is the *dispatched step* (or any section wrapping device work).
A hang shows up as ``block_until_ready`` never returning — e.g. a peer host
died mid all-reduce over DCN. The watchdog arms a timer around each watched
section; on expiry it writes a diagnostic dump (section name, elapsed,
recent section history, all Python thread stacks) and either calls the
user's handler or aborts (``os._exit``) like the reference's error dump +
abort path.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

__all__ = ["CommWatchdog", "WatchdogTimeout"]


class WatchdogTimeout(RuntimeError):
    pass


class CommWatchdog:
    """Watch device-work sections for hangs.

    Usage::

        wd = CommWatchdog(timeout=1800, abort=True)
        for batch in loader:
            with wd.section("train_step"):
                loss = train_step(model, opt, batch)   # blocks on device
    """

    def __init__(
        self,
        timeout: float = 1800.0,
        on_timeout: Optional[Callable[[Dict[str, Any]], None]] = None,
        abort: bool = False,
        history: int = 64,
    ) -> None:
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self.abort = abort
        self.completed: Deque[Dict[str, Any]] = deque(maxlen=history)
        # the most recent timeout dump, exposed so a resilient loop (or a
        # test) can assert on WHAT fired without scraping stderr
        self.last_dump: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._seq = 0

    # -- dump ---------------------------------------------------------------
    def _dump(self, name: str, started: float) -> Dict[str, Any]:
        stacks: Dict[str, List[str]] = {}
        for tid, frame in sys._current_frames().items():
            stacks[str(tid)] = traceback.format_stack(frame)
        return {
            "section": name,
            "elapsed_s": time.monotonic() - started,
            "timeout_s": self.timeout,
            "pid": os.getpid(),
            "recent_sections": list(self.completed),
            "thread_stacks": stacks,
        }

    def _write_stderr(self, dump: Dict[str, Any]) -> None:
        sys.stderr.write(
            f"[CommWatchdog] section '{dump['section']}' exceeded "
            f"{self.timeout}s — probable collective hang. Recent sections: "
            f"{[s['section'] for s in dump['recent_sections']]}\n"
        )
        for tid, st in dump["thread_stacks"].items():
            sys.stderr.write(f"--- thread {tid} ---\n{''.join(st)}\n")
        sys.stderr.flush()

    def _fire(self, name: str, started: float, done: threading.Event) -> None:
        if done.wait(self.timeout):
            return
        dump = self._dump(name, started)
        self.last_dump = dump
        try:
            # the always-on flight recorder gets a black-box line + a
            # postmortem dump file BEFORE any handler/abort runs; both are
            # best-effort by contract (safe_dump swallows its own failures)
            from paddle_tpu.observability import flight_recorder as _flight

            _flight.record_event(
                "watchdog_timeout", section=name,
                elapsed_s=round(dump["elapsed_s"], 3), timeout_s=self.timeout,
            )
            _flight.safe_dump(
                "comm_watchdog_timeout",
                extra={"section": name, "elapsed_s": dump["elapsed_s"],
                       "recent_sections": [
                           s["section"] for s in dump["recent_sections"]]},
            )
        # analysis: disable=EH402 best-effort black box: a broken observability import must never block the dump/abort path; the stderr dump below is the evidence of record
        except Exception:
            pass
        try:
            try:
                if self.on_timeout is not None:
                    self.on_timeout(dump)
                else:
                    self._write_stderr(dump)
            except Exception:
                # a buggy user handler must not suppress the abort path's
                # diagnostics — dump the handler's own failure, then fall
                # back to the default stderr dump so the hang evidence
                # reaches the logs before any abort
                traceback.print_exc(file=sys.stderr)
                self._write_stderr(dump)
        finally:
            if self.abort:
                # the hung collective cannot be cancelled from Python — abort
                # so the launcher/elastic layer can relaunch (reference
                # CommTaskManager timeout dump + abort)
                os._exit(124)

    # -- public -------------------------------------------------------------
    def section(self, name: str = "step") -> "_Section":
        return _Section(self, name)

    def watch(self, fn: Callable, *args: Any, name: Optional[str] = None, **kwargs: Any) -> Any:
        with self.section(name or getattr(fn, "__name__", "step")):
            return fn(*args, **kwargs)


class _Section:
    def __init__(self, wd: CommWatchdog, name: str) -> None:
        self._wd = wd
        self._name = name
        self._done = threading.Event()
        self._started = 0.0

    def __enter__(self) -> "_Section":
        self._started = time.monotonic()
        self._thread = threading.Thread(
            target=self._wd._fire,
            args=(self._name, self._started, self._done),
            daemon=True,
        )
        self._thread.start()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        self._done.set()
        with self._wd._lock:
            self._wd._seq += 1
            self._wd.completed.append(
                {
                    "section": self._name,
                    "seq": self._wd._seq,
                    "duration_s": time.monotonic() - self._started,
                    "ok": exc_type is None,
                    # WHAT failed, not just that it did: lets a resilient
                    # loop / test distinguish a WatchdogTimeout from an OOM
                    # without racing stderr
                    "exc_type": exc_type.__name__ if exc_type is not None else None,
                }
            )
