"""Auto-parallel Strategy (reference
``python/paddle/distributed/auto_parallel/strategy.py:191``): a nested config
tree selecting parallelization/optimization behaviors for the Engine.

The reference's fields configure graph passes; here each field maps onto the
TPU-native mechanism that replaces the pass (GSPMD sharding, autocast
contexts, recompute wrapping, ZeRO optimizer sharding, gradient accumulation
inside the jitted step).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class BaseConfig:
    """Attribute-bag with defaults + dict override (reference BaseConfig)."""

    _defaults: Dict[str, Any] = {}

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        for k, v in self._defaults.items():
            setattr(self, k, v)
        for k, v in (config or {}).items():
            setattr(self, k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._defaults}

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._defaults)
        return f"{type(self).__name__}({inner})"


class AmpConfig(BaseConfig):
    _defaults = {
        "enable": False,
        "dtype": "bfloat16",
        "level": "o1",
        "init_loss_scaling": 32768.0,
        "use_master_weights": True,
    }


class ShardingConfig(BaseConfig):
    _defaults = {"enable": False, "stage": 1, "degree": 8}


class RecomputeConfig(BaseConfig):
    _defaults = {"enable": False, "refined_ops": None}


class PipelineConfig(BaseConfig):
    _defaults = {
        "enable": False,
        "schedule_mode": "1F1B",
        "accumulate_steps": 1,
        "micro_batch_size": None,
    }


class GradientMergeConfig(BaseConfig):
    _defaults = {"enable": False, "k_steps": 1, "avg": True}


class FusedPassesConfig(BaseConfig):
    # XLA fuses; kept for API parity (scripts read/write these fields)
    _defaults = {"enable": False, "fused_passes_list": None}


class Strategy(BaseConfig):
    """Top-level strategy (reference ``strategy.py:191``): ``strategy.amp``,
    ``strategy.sharding``, ``strategy.recompute``, ``strategy.pipeline``,
    ``strategy.gradient_merge``, ``strategy.fused_passes``."""

    _defaults = {"auto_mode": "semi", "seed": None}

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        config = dict(config or {})
        self.amp = AmpConfig(config.pop("amp", None))
        self.sharding = ShardingConfig(config.pop("sharding", None))
        self.recompute = RecomputeConfig(config.pop("recompute", None))
        self.pipeline = PipelineConfig(config.pop("pipeline", None))
        self.gradient_merge = GradientMergeConfig(config.pop("gradient_merge", None))
        self.fused_passes = FusedPassesConfig(config.pop("fused_passes", None))
        super().__init__(config)

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        for name in ("amp", "sharding", "recompute", "pipeline", "gradient_merge", "fused_passes"):
            d[name] = getattr(self, name).to_dict()
        return d
