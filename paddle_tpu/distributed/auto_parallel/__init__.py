"""Auto-parallel: declarative Engine + Strategy over GSPMD.

Reference: ``python/paddle/distributed/auto_parallel/`` — the static Engine
(``static/engine.py:96``) and Strategy (``strategy.py:191``). The dygraph
semi-auto API (shard_tensor/reshard/shard_layer) lives in
``paddle_tpu.distributed.api``.
"""

from paddle_tpu.distributed.auto_parallel.engine import Engine  # noqa: F401
from paddle_tpu.distributed.auto_parallel.strategy import Strategy  # noqa: F401
