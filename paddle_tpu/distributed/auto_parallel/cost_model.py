"""Analytic cost model v1 for parallel-config selection.

Reference: ``python/paddle/distributed/auto_parallel/static/cost/`` (op-level
FLOPs/bytes/comm estimation feeding the static planner). TPU-native redesign:
instead of per-op cost tables over a program IR, the model prices a whole
transformer training step from the model config + mesh factorization — FLOPs
on the MXU, collective bytes over ICI, the pipeline bubble, and a per-micro-
batch dispatch overhead. That is the granularity the auto_tuner and Engine
actually choose between (dp/mp/pp/sharding/micro-batch/recompute), and it
needs no tracing.

All knobs are overridable through ``tuner_cfg``:
  ``peak_flops``   chip peak (default 197e12, v5e bf16)
  ``mfu``          achievable matmul efficiency (default 0.4)
  ``ici_bw``       per-link ICI bandwidth, bytes/s (default 9e10)
  ``step_overhead`` fixed per-microbatch dispatch/launch cost (default 1e-4 s)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

__all__ = ["estimate_step_time", "rank_configs", "validate_ranking"]


def _params(model: Dict[str, Any]) -> float:
    layers = int(model.get("num_layers", 0) or 0)
    hidden = int(model.get("hidden_size", 0) or 0)
    vocab = int(model.get("vocab_size", 0) or 0)
    inter = int(model.get("intermediate_size", 4 * hidden) or 4 * hidden)
    return float(layers * (4 * hidden * hidden + 3 * hidden * inter) + 2 * vocab * hidden)


def estimate_step_time(cfg: Dict[str, Any], tuner_cfg: Dict[str, Any]) -> Dict[str, float]:
    """Price one global-batch training step for ``cfg`` on the chips described
    by ``tuner_cfg``. Returns the breakdown; ``step_time_s`` is the total."""
    model = tuner_cfg.get("model_cfg", {}) or {}
    n = _params(model)
    seq = int(model.get("seq_length", 2048) or 2048)
    hidden = int(model.get("hidden_size", 1) or 1)
    layers = int(model.get("num_layers", 1) or 1)
    gbs = int(tuner_cfg.get("global_batch_size", 1) or 1)

    peak = float(tuner_cfg.get("peak_flops", 197e12))
    mfu = float(tuner_cfg.get("mfu", 0.4))
    bw = float(tuner_cfg.get("ici_bw", 9e10))
    overhead = float(tuner_cfg.get("step_overhead", 1e-4))

    dp = int(cfg.get("dp_degree", 1))
    mp = int(cfg.get("mp_degree", 1))
    pp = int(cfg.get("pp_degree", 1))
    shard = max(1, int(cfg.get("sharding_degree", 1)))
    mbs = int(cfg.get("micro_batch_size", 1))
    acc = int(cfg.get("acc_steps", max(1, (gbs // max(dp, 1)) // max(mbs, 1))))
    rc = bool(cfg.get("use_recompute", False))

    tokens = gbs * seq
    # fwd+bwd weight FLOPs: 6*N per token; recompute re-runs the forward (+2N)
    flops_per_token = (8.0 if rc else 6.0) * n
    compute = flops_per_token * tokens / (dp * mp * pp) / (peak * mfu)

    # pipeline bubble (1F1B / circular): (M + S - 1) / M serialization
    micro = max(acc, 1)
    bubble = (micro + pp - 1) / micro if pp > 1 else 1.0
    compute *= bubble

    act_bytes = 2.0 * mbs * seq * hidden  # one bf16 activation tensor
    comm = 0.0
    if mp > 1:
        # megatron TP: 2 all-reduces per layer fwd + 2 bwd, ring cost
        per_ar = 2.0 * (mp - 1) / mp * act_bytes / bw
        comm += 4.0 * per_ar * (layers / pp) * micro
    if pp > 1:
        # p2p activation sends along the ring, fwd + bwd
        comm += 2.0 * (micro + pp - 1) * act_bytes / bw
    grad_bytes = 4.0 * n / (mp * pp)
    if dp > 1:
        # gradient sync once per global step; under sharding the sync is a
        # reduce-scatter + all-gather over the sharding group, which moves
        # the SAME ring bytes as one all-reduce — it replaces, never adds
        comm += 2.0 * (dp - 1) / dp * grad_bytes / bw
    elif shard > 1:
        comm += 2.0 * (shard - 1) / shard * grad_bytes / bw

    dispatch = overhead * micro
    total = compute + comm + dispatch
    return {
        "step_time_s": total,
        "compute_s": compute,
        "comm_s": comm,
        "dispatch_s": dispatch,
        "bubble_factor": bubble,
    }


def rank_configs(
    cfgs: Sequence[Dict[str, Any]], tuner_cfg: Dict[str, Any]
) -> List[Dict[str, Any]]:
    """Fastest-predicted first; each config gains a ``cost_estimate`` entry."""
    out = []
    for c in cfgs:
        c = dict(c)
        c["cost_estimate"] = estimate_step_time(c, tuner_cfg)["step_time_s"]
        out.append(c)
    out.sort(key=lambda c: c["cost_estimate"])
    return out


def validate_ranking(
    estimated: Sequence[float], measured: Sequence[float]
) -> float:
    """Spearman rank correlation between predicted and measured step times."""
    import numpy as np

    e = np.argsort(np.argsort(estimated)).astype(float)
    m = np.argsort(np.argsort(measured)).astype(float)
    if e.std() == 0 or m.std() == 0:
        return 0.0
    return float(np.corrcoef(e, m)[0, 1])
