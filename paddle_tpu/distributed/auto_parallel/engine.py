"""Static auto-parallel Engine (reference
``python/paddle/distributed/auto_parallel/static/engine.py:96``).

The reference Engine takes a dygraph model + loss + optimizer + Strategy,
builds a distributed static Program through completion/partitioner/pass
pipeline, and drives fit/evaluate/predict. TPU-native redesign: the
"completion + partition" step IS GSPMD — the Engine annotates parameters with
mesh shardings (user ``shard_fn`` or replicate-by-default), annotates batch
inputs with the data-parallel sharding, jit-compiles one whole train step
(fwd + loss + bwd + optimizer under donation), and lets XLA insert the
collectives. Strategy fields map to the TPU mechanisms:

- ``strategy.amp``        → autocast context (+ master weights in AdamW)
- ``strategy.recompute``  → fleet recompute() around the forward
- ``strategy.sharding``   → ZeRO: optimizer-state placements follow params
- ``strategy.gradient_merge`` → micro-step accumulation inside the fit loop
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.distributed.auto_parallel.strategy import Strategy

__all__ = ["Engine", "Strategy"]


class Engine:
    def __init__(
        self,
        model: Any = None,
        loss: Any = None,
        optimizer: Any = None,
        metrics: Any = None,
        cluster: Any = None,
        strategy: Optional[Strategy] = None,
    ) -> None:
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = (
            [] if metrics is None else (metrics if isinstance(metrics, (list, tuple)) else [metrics])
        )
        self._cluster = cluster  # may carry a ProcessMesh
        self._strategy = strategy or Strategy()
        self._mesh = None
        self._shard_fn: Optional[Callable] = None
        self._prepared = False
        self._train_step = None
        self._eval_step = None
        self._pred_step = None
        self.history: Dict[str, List[float]] = {"loss": []}

    # ------------------------------------------------------------------ prep
    def prepare(
        self,
        inputs_spec: Any = None,
        labels_spec: Any = None,
        mesh: Any = None,
        shard_fn: Optional[Callable] = None,
        mode: str = "train",
    ) -> None:
        """Annotate the model over the mesh and build the compiled steps.

        ``mesh``: a ProcessMesh (defaults to the globally-set mesh via
        ``dist.set_mesh``, else a 1-D data-parallel mesh over all devices).
        ``shard_fn(name, sublayer, mesh)``: per-layer placement rule (e.g.
        ``gpt_shard_fn``); parameters it leaves untouched stay replicated.
        """
        import jax

        import paddle_tpu.distributed as dist

        if self._prepared:
            return
        if mesh is None:
            mesh = self._cluster if self._cluster is not None else dist.get_mesh()
        if mesh is None:
            n = len(jax.devices())
            mesh = dist.ProcessMesh(shape=[n], dim_names=["dp"], process_ids=list(range(n)))
        self._mesh = mesh
        self._shard_fn = shard_fn
        if self._strategy.seed is not None:
            import paddle_tpu as paddle

            paddle.seed(int(self._strategy.seed))
        if shard_fn is not None and self._model is not None:
            for name, sub in self._model.named_sublayers(include_self=True):
                shard_fn(name, sub, mesh)
        if self._model is not None:
            # every operand must live on the mesh's device set: params the
            # shard_fn left untouched (or all of them, with no shard_fn) get
            # replicated — the "completion" step of the reference's
            # completer, done by placement instead of annotation inference
            from jax.sharding import NamedSharding

            from paddle_tpu.distributed.api import apply_placement
            from paddle_tpu.distributed.placements import Replicate

            jmesh = mesh.jax_mesh()
            repl = [Replicate() for _ in mesh.dim_names]
            for p in self._model.parameters():
                sh = getattr(p._data, "sharding", None)
                if not (isinstance(sh, NamedSharding) and sh.mesh == jmesh):
                    apply_placement(p, mesh, repl)
        if (
            self._strategy.amp.enable
            and str(self._strategy.amp.level).lower() == "o2"
            and self._optimizer is not None
        ):
            import paddle_tpu as paddle

            self._model, self._optimizer = paddle.amp.decorate(
                self._model, self._optimizer, level="O2", dtype=self._strategy.amp.dtype
            )
        if self._strategy.sharding.enable and self._optimizer is not None:
            dist.shard_optimizer(self._optimizer)
        self._prepared = True

    # ---------------------------------------------------------------- helpers
    def _dp_placements(self) -> List[Any]:
        from paddle_tpu.distributed.placements import Replicate, Shard

        names = list(self._mesh.dim_names)
        dp_axis = 0
        for cand in ("dp", "data", "batch"):
            if cand in names:
                dp_axis = names.index(cand)
                break
        return [Shard(0) if i == dp_axis else Replicate() for i in range(len(names))]

    def _shard_batch(self, t: Any) -> Any:
        import paddle_tpu.distributed as dist
        from paddle_tpu.core.tensor import Tensor

        if not isinstance(t, Tensor):
            return t
        try:
            return dist.shard_tensor(t, self._mesh, self._dp_placements())
        except Exception:  # noqa: BLE001 - unshardable (batch % dp != 0): replicate
            return t

    def _forward(self, *features: Any) -> Any:
        s = self._strategy
        model = self._model
        if s.recompute.enable:
            from paddle_tpu.distributed.fleet.recompute import recompute

            return recompute(model, *features)
        return model(*features)

    def _compute_loss(self, out: Any, label: Any) -> Any:
        if self._loss is None:
            raise ValueError("Engine needs a loss for train/eval mode")
        loss = self._loss(out, label)
        if isinstance(loss, (list, tuple)):
            loss = loss[0]
        return loss

    def _build_train_step(self) -> Callable:
        import paddle_tpu as paddle

        s = self._strategy
        engine = self

        @paddle.jit.to_static
        def train_step(model, opt, *batch: Any):
            *features, label = batch
            if s.amp.enable:
                with paddle.amp.auto_cast(
                    level=str(s.amp.level).upper(), dtype=s.amp.dtype
                ):
                    out = engine._forward(*features)
                    loss = engine._compute_loss(out, label)
            else:
                out = engine._forward(*features)
                loss = engine._compute_loss(out, label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return train_step

    def _build_eval_step(self) -> Callable:
        import paddle_tpu as paddle

        engine = self

        @paddle.jit.to_static
        def eval_step(model, *batch: Any):
            *features, label = batch
            with paddle.no_grad():
                out = engine._forward(*features)
                loss = engine._compute_loss(out, label)
            return loss, out

        return eval_step

    def _build_pred_step(self) -> Callable:
        import paddle_tpu as paddle

        engine = self

        @paddle.jit.to_static
        def pred_step(model, *features: Any):
            with paddle.no_grad():
                return engine._forward(*features)

        return pred_step

    def _loader(self, data: Any, batch_size: int, shuffle: bool) -> Any:
        from paddle_tpu.io import DataLoader, Dataset

        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle, drop_last=True)
        return data  # any iterable of batches

    @staticmethod
    def _as_batch(batch: Any) -> Sequence[Any]:
        if isinstance(batch, (list, tuple)):
            flat: List[Any] = []
            for b in batch:
                if isinstance(b, (list, tuple)):
                    flat.extend(b)
                else:
                    flat.append(b)
            return flat
        return [batch]

    # ------------------------------------------------------------------ modes
    def fit(
        self,
        train_data: Any,
        train_sample_split: Any = None,
        batch_size: int = 1,
        epochs: int = 1,
        steps_per_epoch: Optional[int] = None,
        log_freq: int = 10,
        save_dir: Optional[str] = None,
        verbose: int = 1,
        collate_fn: Any = None,
    ) -> Dict[str, List[float]]:
        """Train over ``train_data`` (Dataset / DataLoader / iterable of
        batches, each batch ``(*features, label)``). Returns the history."""
        if self._model is None or self._optimizer is None:
            raise ValueError("Engine.fit needs model and optimizer")
        self.prepare()
        if self._train_step is None:
            self._train_step = self._build_train_step()
        loader = self._loader(train_data, batch_size, shuffle=True)
        k_steps = max(1, int(self._strategy.gradient_merge.k_steps)) if self._strategy.gradient_merge.enable else 1
        for _epoch in range(epochs):
            epoch_step = 0
            for batch in loader:
                parts = [self._shard_batch(b) for b in self._as_batch(batch)]
                if k_steps > 1:
                    # gradient merge: accumulate k micro-steps, then step once
                    loss = self._accumulate_step(parts, k_steps)
                else:
                    loss = self._train_step(self._model, self._optimizer, *parts)
                self.history["loss"].append(float(loss))
                epoch_step += 1
                if steps_per_epoch is not None and epoch_step >= steps_per_epoch:
                    break
        if k_steps > 1:
            self._flush_merge_bufs(k_steps)
        if save_dir:
            self.save(save_dir)
        return self.history

    def _flush_merge_bufs(self, k: int) -> None:
        """Apply any partial gradient-merge window left when fit() ends (total
        steps not a multiple of k). Without this the tail micro-batches'
        grads would be dropped AND leak into the next fit()'s first window."""
        count = getattr(self, "_merge_count", 0)
        if not count or getattr(self, "_merge_bufs", None) is None:
            self._merge_bufs = None
            self._merge_count = 0
            return
        import warnings

        warnings.warn(
            f"gradient_merge: applying a partial window of {count}/{k} "
            "micro-batches at end of fit()",
            stacklevel=3,
        )
        # with avg=True each micro-loss was pre-divided by k; rescale so the
        # partial window is the mean over `count` micro-batches
        scale = float(k) / float(count) if self._strategy.gradient_merge.avg else 1.0
        trainable = [p for p in self._model.parameters() if not p.stop_gradient]
        for p, g in zip(trainable, self._merge_bufs):
            if g is not None:
                p.grad = g * scale if scale != 1.0 else g
        self._optimizer.step()
        self._optimizer.clear_grad()
        self._merge_bufs = None
        self._merge_count = 0

    def _accumulate_step(self, parts: Sequence[Any], k: int) -> Any:
        """Gradient merge (reference ``gradient_merge_pass``): k jitted
        micro-steps each RETURN their grads (jit state capture does not
        persist ``.grad`` side effects); the Engine accumulates them in device
        buffers and applies one optimizer step on the k-th micro-batch."""
        import paddle_tpu as paddle

        engine = self
        s = self._strategy

        if getattr(self, "_accum_step_fn", None) is None:

            @paddle.jit.to_static
            def accum_step(model, *batch: Any):
                *features, label = batch
                out = engine._forward(*features)
                loss = engine._compute_loss(out, label)
                if s.gradient_merge.avg:
                    (loss / float(k)).backward()
                else:
                    loss.backward()
                grads = [
                    p.grad if p.grad is not None else None
                    for p in model.parameters()
                    if not p.stop_gradient
                ]
                model.clear_gradients()  # nothing escapes the trace
                return loss, grads

            self._accum_step_fn = accum_step
            self._merge_bufs = None
            self._merge_count = 0
        loss, grads = self._accum_step_fn(self._model, *parts)
        if self._merge_bufs is None:
            self._merge_bufs = list(grads)
            self._merge_count = 1
        else:
            self._merge_bufs = [
                g if b is None else (b if g is None else b + g)
                for b, g in zip(self._merge_bufs, grads)
            ]
            self._merge_count += 1
        # key the apply on the ACCUMULATED count, not the global step index —
        # a steps_per_epoch break mid-window must not desync later windows
        if self._merge_count >= k:
            trainable = [p for p in self._model.parameters() if not p.stop_gradient]
            for p, g in zip(trainable, self._merge_bufs):
                if g is not None:
                    p.grad = g
            self._optimizer.step()
            self._optimizer.clear_grad()
            self._merge_bufs = None
            self._merge_count = 0
        return loss

    def evaluate(
        self,
        valid_data: Any,
        valid_sample_split: Any = None,
        batch_size: int = 1,
        steps: Optional[int] = None,
        log_freq: int = 10,
        verbose: int = 1,
        collate_fn: Any = None,
    ) -> Dict[str, float]:
        self.prepare()
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        loader = self._loader(valid_data, batch_size, shuffle=False)
        losses: List[float] = []
        for m in self._metrics:
            m.reset()
        for i, batch in enumerate(loader):
            parts = [self._shard_batch(b) for b in self._as_batch(batch)]
            loss, out = self._eval_step(self._model, *parts)
            losses.append(float(loss))
            for m in self._metrics:
                m.update(m.compute(out, parts[-1]))
            if steps is not None and i + 1 >= steps:
                break
        result = {"eval_loss": float(np.mean(losses)) if losses else float("nan")}
        for m in self._metrics:
            result[m.name() if callable(getattr(m, "name", None)) else "metric"] = m.accumulate()
        return result

    def predict(
        self,
        test_data: Any,
        test_sample_split: Any = None,
        batch_size: int = 1,
        steps: Optional[int] = None,
        verbose: int = 1,
        collate_fn: Any = None,
    ) -> List[Any]:
        self.prepare()
        if self._pred_step is None:
            self._pred_step = self._build_pred_step()
        loader = self._loader(test_data, batch_size, shuffle=False)
        outs: List[Any] = []
        for i, batch in enumerate(loader):
            parts = [self._shard_batch(b) for b in self._as_batch(batch)]
            if test_sample_split is not None:
                # reference Engine semantics: sample[:split] are the inputs,
                # sample[split:] are labels — predict feeds inputs only
                parts = parts[: int(test_sample_split)]
            outs.append(self._pred_step(self._model, *parts))
            if steps is not None and i + 1 >= steps:
                break
        return outs

    # ------------------------------------------------------------------- io
    def save(self, path: str, training: bool = True) -> None:
        import paddle_tpu as paddle

        state = {k: v for k, v in self._model.state_dict().items()}
        paddle.save(state, path + ".pdparams")
        if training and self._optimizer is not None and hasattr(self._optimizer, "state_dict"):
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, strict: bool = True, load_optimizer: bool = True) -> None:
        import os

        import paddle_tpu as paddle

        state = paddle.load(path + ".pdparams")
        self._model.set_state_dict(state)
        opt_path = path + ".pdopt"
        if load_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(paddle.load(opt_path))

    def cost(self, model_cfg: Dict[str, Any], global_batch_size: int = 1,
             **knobs: Any) -> Dict[str, float]:
        """Analytic step-time estimate for THIS engine's mesh + strategy
        (reference ``auto_parallel/static/cost/``): a sanity check that the
        chosen sharding isn't comm- or bubble-dominated before training."""
        from paddle_tpu.distributed.auto_parallel.cost_model import estimate_step_time

        mesh = self._mesh
        shape = dict(zip(mesh.dim_names, mesh.shape)) if mesh is not None else {}
        s = self._strategy
        acc = s.gradient_merge.k_steps if s.gradient_merge.enable else 1
        dp = max(shape.get("dp", 1), 1)
        cfg = {
            "dp_degree": dp,
            "mp_degree": shape.get("mp", shape.get("tp", 1)),
            "pp_degree": shape.get("pp", 1),
            "sharding_degree": dp if s.sharding.enable else 1,
            "sharding_stage": s.sharding.stage if s.sharding.enable else 1,
            "use_recompute": s.recompute.enable,
            # the per-dp batch splits into acc micro-batches
            "micro_batch_size": max(1, global_batch_size // (dp * acc)),
            "acc_steps": acc,
        }
        tuner_cfg = {"model_cfg": model_cfg, "global_batch_size": global_batch_size}
        tuner_cfg.update(knobs)
        return estimate_step_time(cfg, tuner_cfg)

    # parity introspection
    @property
    def strategy(self) -> Strategy:
        return self._strategy

    @property
    def mesh(self) -> Any:
        return self._mesh
