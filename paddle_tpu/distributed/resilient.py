"""Resilient training loop: CommWatchdog + crash-consistent checkpoint resume.

Reference shape: the fork's ``CommTaskManager`` (detect → dump → abort →
relaunch) plus its elastic manager's relaunch-with-checkpoint contract. Two
failure regimes compose here:

- **in-process recoverable** — a step raises (backend error, watchdog-raised
  ``WatchdogTimeout``, injected fault): restore the last *valid* checkpoint
  (``CheckpointManager.latest_valid()`` skips torn ones) and resume from the
  step after it, up to ``max_failures`` times;
- **process-fatal** — a true hang: the ``CommWatchdog`` section around each
  step dumps diagnostics and (when ``abort=True``) exits so the launcher /
  elastic layer relaunches the process — on the next life this same loop
  finds the checkpoint and resumes.

The loop checkpoints ``state_dict`` (plus the optimizer's state and the step
counter) every ``save_every`` steps through :class:`CheckpointManager`, whose
atomic-commit discipline guarantees the resume source is never a torn file.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Type

from paddle_tpu.distributed.checkpoint.manager import CheckpointManager
from paddle_tpu.distributed.watchdog import CommWatchdog, WatchdogTimeout

__all__ = ["resilient_train_loop"]

_OPT_PREFIX = "optim::"


def _full_state(state_dict: Dict[str, Any], optimizer: Any) -> Dict[str, Any]:
    sd = dict(state_dict)
    if optimizer is not None:
        for k, v in optimizer.state_dict().items():
            sd[_OPT_PREFIX + k] = v
    return sd


def _restore(
    manager: CheckpointManager,
    state_dict: Dict[str, Any],
    optimizer: Any,
    step: int,
) -> Dict[str, Any]:
    target = _full_state(state_dict, optimizer)
    for k in manager.manifest_keys(step):
        # placeholders for checkpoint keys the live objects don't hold yet
        # (e.g. optimizer accumulators on a fresh relaunch): restore returns
        # them as host arrays / native values
        target.setdefault(k, None)
    info = manager.restore(target, step=step)
    for k, v in target.items():
        if not k.startswith(_OPT_PREFIX):
            # Tensor entries were filled in place (v is state_dict[k]);
            # plain entries were replaced — write the restored value back
            state_dict[k] = v
    if optimizer is not None:
        optimizer.set_state_dict(
            {k[len(_OPT_PREFIX):]: v for k, v in target.items()
             if k.startswith(_OPT_PREFIX)}
        )
    return info


def resilient_train_loop(
    step_fn: Callable[[int], Any],
    state_dict: Dict[str, Any],
    num_steps: int,
    manager: CheckpointManager,
    optimizer: Any = None,
    watchdog: Optional[CommWatchdog] = None,
    save_every: int = 1,
    max_failures: int = 3,
    recover_on: Tuple[Type[BaseException], ...] = (
        WatchdogTimeout,
        RuntimeError,  # covers XlaRuntimeError + injected faults
        MemoryError,
        OSError,
    ),
) -> Dict[str, Any]:
    """Run ``step_fn(step)`` for steps ``0..num_steps-1`` with checkpointing
    and resume-on-failure.

    On entry, an existing valid checkpoint (e.g. from a previous life of
    this process) is restored and the loop starts after it. Each completed
    step is checkpointed every ``save_every`` steps; a ``recover_on``
    exception restores the last valid checkpoint and resumes from the step
    after it (or retries from the initial state when nothing was saved yet).
    More than ``max_failures`` recoveries re-raises — a persistent fault
    must escalate to the launcher, not loop forever.

    Returns a summary: ``{"start_step", "failures", "resumes": [...],
    "completed": num_steps}``.
    """
    resumes = []
    failures = 0
    start = 0
    rec = manager.latest_valid()
    if rec is not None:
        info = _restore(manager, state_dict, optimizer, rec.step)
        start = info["step"] + 1
    step = start
    while step < num_steps:
        try:
            if watchdog is not None:
                with watchdog.section(f"train_step_{step}"):
                    step_fn(step)
            else:
                step_fn(step)
            # the save participates in the same recovery policy: a transient
            # disk failure mid-save (its staging discipline left both the
            # live state and the previous checkpoint intact) consumes a
            # failure budget slot and resumes, instead of killing the run
            if save_every and step % save_every == 0:
                manager.save(_full_state(state_dict, optimizer), step)
        except recover_on as exc:
            failures += 1
            if failures > max_failures:
                raise
            rec = manager.latest_valid()
            resumes.append(
                {
                    "failed_step": step,
                    "error": f"{type(exc).__name__}: {exc}"[:200],
                    "resumed_from": rec.step if rec is not None else None,
                }
            )
            if rec is not None:
                info = _restore(manager, state_dict, optimizer, rec.step)
                step = info["step"] + 1
            # no checkpoint yet: retry the same step from the live state
            continue
        step += 1
    return {
        "start_step": start,
        "completed": int(num_steps),
        "failures": failures,
        "resumes": resumes,
    }
