"""Parallel environment + DataParallel.

Reference: ``python/paddle/distributed/parallel.py`` (``init_parallel_env:978``,
``DataParallel:219``). TPU-native model: single-controller SPMD — one Python
process drives all chips; "rank" is the process index (multi-host) and
data-parallelism is expressed by sharding the batch over a mesh axis, with
gradient reduction handled by XLA's sharding propagation instead of an
EagerReducer + NCCL allreduce (``paddle/fluid/distributed/collective/reducer.cc``).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import ProcessMesh, get_mesh, init_mesh
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "init_parallel_env",
    "get_rank",
    "get_world_size",
    "is_initialized",
    "DataParallel",
    "ParallelEnv",
]

_initialized = [False]


def init_parallel_env() -> "ParallelEnv":
    """Initialize the distributed runtime. Multi-host: wires
    ``jax.distributed`` from env vars (coordination service = the TCPStore
    analog); single-host: no-op beyond mesh defaulting."""
    if _initialized[0]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(
        os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("PADDLE_NNODES", "1"))
    )
    if coord and nprocs > 1:  # pragma: no cover - requires real multi-host
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        )
    if get_mesh() is None:
        init_mesh(["dp"], [len(jax.devices())])
    _initialized[0] = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized[0]


def get_rank(group: Any = None) -> int:
    return jax.process_index()


def get_world_size(group: Any = None) -> int:
    if group is not None and hasattr(group, "nranks"):
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return get_rank()


class DataParallel(Layer):
    """Data-parallel wrapper (reference ``parallel.py:219``).

    Shards the leading (batch) dim of inputs over the 'dp' mesh axis and keeps
    parameters replicated. Gradient all-reduce is implicit: contracting a
    batch-sharded activation against a replicated parameter in backward makes
    XLA emit the reduction (the EagerReducer's fused allreduce, moved into the
    compiler).
    """

    def __init__(
        self,
        layers: Layer,
        strategy: Any = None,
        comm_buffer_size: int = 25,
        last_comm_buffer_size: int = 1,
        find_unused_parameters: bool = False,
        group: Any = None,
    ) -> None:
        super().__init__()
        self._layers = layers
        mesh = get_mesh()
        if mesh is None:
            mesh = init_mesh(["dp"], [len(jax.devices())])
        self._mesh = mesh
        self._dp_axis = mesh.dim_names[0]
        # replicate parameters across the mesh
        from paddle_tpu.distributed.api import shard_tensor
        from paddle_tpu.distributed.placements import Replicate

        import paddle_tpu

        with paddle_tpu.no_grad():
            for p in layers.parameters():
                d = shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])
                p._data = d._data

    def _shard_input(self, x: Any) -> Any:
        if not isinstance(x, Tensor) or x.ndim == 0:
            return x
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(self._dp_axis, *([None] * (x.ndim - 1)))
        arr = jax.device_put(x._data, NamedSharding(self._mesh.jax_mesh(), spec))
        out = Tensor(arr, stop_gradient=x.stop_gradient)
        return out

    def forward(self, *inputs: Any, **kwargs: Any) -> Any:
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args: Any, **kwargs: Any) -> Any:
        return self._layers.set_state_dict(*args, **kwargs)

    def scale_loss(self, loss: Tensor) -> Tensor:
        return loss

    def apply_collective_grads(self) -> None:
        """No-op: gradient reduction is emitted by XLA (see class docstring)."""
