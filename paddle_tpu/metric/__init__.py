"""Training metrics (reference ``python/paddle/metric/metrics.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self, name: Optional[str] = None) -> None:
        self._name = name or self.__class__.__name__

    def reset(self) -> None:
        raise NotImplementedError

    def update(self, *args: Any) -> Any:
        raise NotImplementedError

    def accumulate(self) -> Any:
        raise NotImplementedError

    def name(self) -> str:
        return self._name

    def compute(self, pred: Any, label: Any, *args: Any) -> Any:
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name: Optional[str] = None) -> None:
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self) -> None:
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred: Any, label: Any, *args: Any) -> Any:
        pred_np = np.asarray(pred.numpy() if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        maxk = max(self.topk)
        order = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = order == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct: Any) -> float:
        c = np.asarray(correct.numpy() if isinstance(correct, Tensor) else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            self.total[i] += float(num)
            self.count[i] += int(np.prod(c.shape[:-1]))
            accs.append(float(num) / max(int(np.prod(c.shape[:-1])), 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self) -> Any:
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


def _positive_scores(preds: Any, num_labels: int) -> np.ndarray:
    """Positive-class score per sample (reference
    ``python/paddle/metric/metrics.py`` Precision/Recall semantics).

    Two-column rows ``[N, 2]`` with exactly N labels are binary-classifier
    outputs: softmax column 1 is the positive probability (softmax keeps the
    0.5 threshold equivalent to argmax, so raw logits work too). Anything
    else is an elementwise positive probability."""
    p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
    if p.ndim >= 2 and p.shape[-1] == 2 and p[..., 0].size == num_labels:
        shifted = p - p.max(axis=-1, keepdims=True)
        e = np.exp(shifted)
        return (e[..., 1] / e.sum(axis=-1)).reshape(-1)
    return p.reshape(-1)


class Precision(Metric):
    def __init__(self, name: Optional[str] = None) -> None:
        super().__init__(name or "precision")
        self.reset()

    def reset(self) -> None:
        self.tp = 0
        self.fp = 0

    def update(self, preds: Any, labels: Any) -> None:
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)  # noqa: E741
        p = _positive_scores(preds, l.size)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self) -> float:
        return self.tp / max(self.tp + self.fp, 1)


class Recall(Metric):
    def __init__(self, name: Optional[str] = None) -> None:
        super().__init__(name or "recall")
        self.reset()

    def reset(self) -> None:
        self.tp = 0
        self.fn = 0

    def update(self, preds: Any, labels: Any) -> None:
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)  # noqa: E741
        p = _positive_scores(preds, l.size)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self) -> float:
        return self.tp / max(self.tp + self.fn, 1)


class Auc(Metric):
    def __init__(self, curve: str = "ROC", num_thresholds: int = 4095, name: Optional[str] = None) -> None:
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self) -> None:
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds: Any, labels: Any) -> None:
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)  # noqa: E741
        pos_prob = p[:, 1] if p.ndim == 2 else p.reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype(np.int64)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self) -> float:
        tot_pos = tot_neg = auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            auc += self._stat_neg[i] * (tot_pos + self._stat_pos[i] / 2.0)
            tot_pos += self._stat_pos[i]
            tot_neg += self._stat_neg[i]
        return auc / (tot_pos * tot_neg) if tot_pos * tot_neg > 0 else 0.0


def accuracy(input: Any, label: Any, k: int = 1) -> Tensor:  # noqa: A002
    """Top-k accuracy op (reference ``paddle.metric.accuracy``)."""
    from paddle_tpu.core.dispatch import call_op
    import jax
    import jax.numpy as jnp

    def _impl(x, l):  # noqa: E741
        _, idx = jax.lax.top_k(x, k)
        lbl = l[..., 0] if l.ndim == x.ndim and l.shape[-1] == 1 else l
        correct = jnp.any(idx == lbl[..., None], axis=-1)
        return jnp.mean(correct.astype(jnp.float32))

    return call_op("accuracy", _impl, input, label)
